#!/usr/bin/env python
"""True multi-process training on one host (SURVEY §5 distributed backend).

The multi-host wiring (parallel/multihost.py: jax.distributed.initialize,
hybrid DCN x ICI mesh, cross-process agreement, per-process corpus shards
assembled into global arrays) had only ever been unit-tested in factored
form. This harness EXECUTES it: it spawns N real processes on this host,
each with its own corpus shard and its own set of virtual CPU devices,
coordinated through jax.distributed over localhost — exercising
initialize_from_env, make_global_mesh (create_hybrid_device_mesh),
global_agree_sum (batch auto-sizing), global_agree_min (steps/epoch
agreement), make_array_from_process_local_data (global batch assembly),
and assemble_local_replica (process-0-only save) end to end.

Then it trains the IDENTICAL config single-process on the same global
device count and corpus, and compares eval scores (planted-topic Spearman /
neighbor purity / cosine margin) between the two runs. The trajectories
are not bitwise comparable — the multi-process row order interleaves shards
by process rank — so the gate is statistical, like benchmarks/parity.py.

One JSON line to stdout:
    python benchmarks/multiproc.py [--procs 2] [--devices-per-proc 4]

Chaos mode (`--chaos 'peer_dead@8'`): the kill-one-of-N drill for the
distributed watchdog (resilience/watchdog.py). One rank gets the fault
(SIGKILL at a step boundary — a LOST host, no cooperative anything); every
rank runs with --step-deadline/--sync-deadline. The drill asserts the
survivors EXIT within the deadlines (EXIT_STALLED from the step watchdog or
EXIT_PREEMPTED from a bounded collective's SyncTimeout) instead of hanging
in a collective the dead peer never joins — the pre-watchdog behavior was
N-1 processes blocked forever. Emits one JSON line with per-rank exit codes
and exit walls; no eval comparison (the run is deliberately truncated).

Elastic mode (`--chaos elastic`, resilience/elastic.py): the same kill, the
OPPOSITE contract — the survivors must NOT exit. They detect the loss via
the bounded collectives, agree on membership at the elastic rendezvous,
snapshot the last integrity-verified checkpoint, and re-form the fleet at
N-1 in place (same pids, new generation); with --elastic-mode shrink+grow
the drill then relaunches the victim and asserts it is admitted back at a
sync boundary (generation 2, world N). Every process must end rc=0 — any
75/76 on the elastic path is a failure. The drill polls the SHARED
checkpoint's step/words counters for an external throughput curve
(pre-kill vs post-shrink vs post-grow words/sec slopes) and, in plain
shrink mode, runs a FRESH (N-1)-process fleet resumed from the same
generation snapshot and asserts the final embeddings are byte-identical —
elastic continuation IS a fresh shrunken resume, provably.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

from parity import eval_vectors  # noqa: E402


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def cli_cmd(train: str, vocab: str, out: str, dp: int, tp: int = 1,
            iters: int = 3, extra=(), method: str = "ns",
            dense_top: int = 0) -> list:
    return [
        sys.executable, "-m", "word2vec_tpu.cli",
        "-train", train, "-read-vocab", vocab, "-output", out,
        "-model", "sg", "-train_method", method,
        "-negative", "5" if method == "ns" else "0",
        "-size", "64", "-window", "5", "-iter", str(iters),
        "-min-count", "5", "-subsample", "1e-4",
        "--backend", "cpu", "--dp", str(dp), "--tp", str(tp), "--quiet",
        *(("--hs-dense-top", str(dense_top)) if dense_top else ()),
        *extra,
    ]


def _run_chaos(args, result, tmp, procs, logs, victim, t0) -> None:
    """Kill-one-of-N: wait for every rank with per-rank exit timing, assert
    the survivors exit within the deadlines, emit one JSON line."""
    import signal as _signal

    from word2vec_tpu.resilience.shutdown import EXIT_PREEMPTED
    from word2vec_tpu.resilience.watchdog import EXIT_STALLED

    result["chaos"] = args.chaos
    result["victim_rank"] = victim
    result["step_deadline_s"] = args.step_deadline
    result["sync_deadline_s"] = args.sync_deadline

    exit_at = {}
    hard_deadline = time.time() + args.timeout
    while len(exit_at) < len(procs) and time.time() < hard_deadline:
        for r, p in enumerate(procs):
            if r not in exit_at and p.poll() is not None:
                exit_at[r] = time.perf_counter() - t0
        time.sleep(0.2)
    hung = sorted(r for r in range(len(procs)) if r not in exit_at)
    for r in hung:
        procs[r].kill()
        procs[r].wait()

    def tail(r):
        logs[r].seek(0)
        return logs[r].read().strip().splitlines()[-8:]

    result["rcs"] = [p.returncode for p in procs]
    result["exit_walls_s"] = {
        str(r): round(exit_at[r], 1) for r in sorted(exit_at)
    }
    if hung:
        result["error"] = (
            f"ranks {hung} still running after {args.timeout:.0f}s — "
            "survivors HUNG instead of aborting"
        )
        result["log_tails"] = [tail(r) for r in hung]
        print(json.dumps(result))
        return

    victim_rc = procs[victim].returncode
    # SIGKILL shows as -9; a sigterm@ chaos spec would exit EXIT_PREEMPTED
    result["victim_rc"] = victim_rc
    if victim_rc not in (-int(_signal.SIGKILL), EXIT_PREEMPTED):
        result["error"] = f"victim rank {victim} exited rc={victim_rc}, " \
                          "expected SIGKILL(-9) or EXIT_PREEMPTED"
        result["log_tails"] = [tail(victim)]
        print(json.dumps(result))
        return

    # survivors: a bounded abort is EXIT_STALLED (step watchdog caught the
    # wedged collective as a missed boundary) or EXIT_PREEMPTED (a bounded
    # agree/heartbeat collective raised SyncTimeout)
    ok_rcs = (EXIT_STALLED, EXIT_PREEMPTED)
    survivors = [r for r in range(len(procs)) if r != victim]
    result["survivor_rcs"] = {str(r): procs[r].returncode for r in survivors}
    # exit budget: the wedge is noticed within max(deadlines) of the
    # victim's death, plus the fire/abort machinery — 3x + slack covers the
    # monitor interval and the bounded final-checkpoint attempt
    budget = 3.0 * max(args.step_deadline, args.sync_deadline) + 10.0
    result["survivor_exit_after_victim_s"] = {
        str(r): round(exit_at[r] - exit_at[victim], 1) for r in survivors
    }
    result["exit_budget_s"] = budget
    bad = [
        r for r in survivors
        if procs[r].returncode not in ok_rcs
        or exit_at[r] - exit_at[victim] > budget
    ]
    if bad:
        result["error"] = (
            f"survivor ranks {bad} did not abort cleanly within the budget"
        )
        result["log_tails"] = [tail(r) for r in bad]
        print(json.dumps(result))
        return

    # how each survivor ended, from its own manifest (stalled | peer_lost)
    shutdowns = {}
    for r in survivors:
        try:
            with open(os.path.join(tmp, f"m{r}", "manifest.json")) as f:
                shutdowns[str(r)] = json.load(f).get("shutdown")
        except (OSError, ValueError):
            shutdowns[str(r)] = None
    result["survivor_shutdowns"] = shutdowns
    # every failure artifact carries its own timeline (PR 6): both survivor
    # abort paths — watchdog stall and SyncTimeout peer loss — dump the
    # flight recorder into the rank's metrics dir (primary-gated like every
    # metrics artifact, so rank 0's presence is the contract; the rest is
    # informational)
    result["survivor_flights"] = {
        str(r): os.path.exists(os.path.join(tmp, f"m{r}", "flight.json"))
        for r in survivors
    }
    result["ok"] = True
    print(json.dumps(result))


def _run_signals(args, result, tmp, procs, logs, straggler, t0) -> None:
    """Fleet signal-plane drill (ISSUE 11 acceptance): N real
    jax.distributed processes share one metrics dir; an injected stall
    stretch slows ONE rank; the drill asserts (a) fleet.json names that
    host as the straggler, (b) the --slo throughput rule escalates
    warn -> breach on rank 0's metrics stream, and (c) the SloEvent is on
    the signal ring of the flight.json the end-of-drill preemption dumps."""
    import json as _json

    from word2vec_tpu.obs.fleet import validate_fleet_doc
    from word2vec_tpu.resilience.shutdown import EXIT_PREEMPTED

    result["chaos"] = "signals"
    result["straggler_rank"] = straggler

    def tail(r):
        logs[r].seek(0)
        return logs[r].read().strip().splitlines()[-10:]

    def fail(msg, ranks=()):
        result["error"] = msg
        result["log_tails"] = [tail(r) for r in ranks]
        print(_json.dumps(result))

    deadline = time.time() + args.timeout
    for r, p in enumerate(procs):
        try:
            p.wait(timeout=max(1.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return fail(f"signals drill hang (> {args.timeout:.0f}s)",
                        range(len(procs)))
    result["wall_s"] = round(time.perf_counter() - t0, 1)
    result["rcs"] = [p.returncode for p in procs]
    # the injected sigterm@30 preempts the WHOLE fleet cooperatively
    if any(rc != EXIT_PREEMPTED for rc in result["rcs"]):
        return fail(
            f"expected every rank to exit {EXIT_PREEMPTED} (the injected "
            f"SIGTERM preemption), got {result['rcs']}", range(len(procs)),
        )
    mdir = os.path.join(tmp, "msig")
    # (a) fleet.json: schema-valid, every host present, straggler named
    try:
        with open(os.path.join(mdir, "fleet.json")) as f:
            doc = _json.load(f)
        counts = validate_fleet_doc(doc)
    except (OSError, ValueError) as e:
        return fail(f"fleet.json invalid/missing: {e}", [0])
    result["fleet"] = {
        "hosts": doc["hosts"],
        "windows": counts["windows"],
        "straggler": doc.get("straggler"),
    }
    if counts["hosts"] != len(procs):
        return fail(f"fleet.json saw {doc['hosts']}, want {len(procs)} "
                    "hosts", [0])
    if not doc.get("straggler") or doc["straggler"]["host"] != straggler:
        return fail(
            f"fleet.json straggler {doc.get('straggler')} does not name "
            f"the injected rank {straggler}", [0, straggler],
        )
    # (b) warn -> breach escalation on rank 0's metrics stream
    try:
        with open(os.path.join(mdir, "metrics.jsonl")) as f:
            recs = [_json.loads(line) for line in f]
    except (OSError, ValueError) as e:
        return fail(f"metrics.jsonl unreadable: {e}", [0])
    slo = [r for r in recs if str(r.get("event", "")).startswith("slo_")]
    result["slo_events"] = [
        {"event": r["event"], "window": r.get("window"),
         "value": r.get("value"), "threshold": r.get("threshold")}
        for r in slo
    ]
    warns = [r for r in slo if r["event"] == "slo_warn"]
    breaches = [r for r in slo if r["event"] == "slo_breach"]
    if not warns or not breaches:
        return fail(f"expected warn AND breach SloEvents, got {slo}", [0])
    if warns[0].get("window") > breaches[0].get("window"):
        return fail(f"escalation out of order: {slo}", [0])
    # (c) the SloEvent is in the flight dump the preemption wrote
    try:
        with open(os.path.join(mdir, "flight.json")) as f:
            flight = _json.load(f)
    except (OSError, ValueError) as e:
        return fail(f"flight.json unreadable: {e}", [0])
    ring_events = [r.get("event") for r in flight.get("signals", [])]
    result["flight"] = {
        "reason": flight.get("reason"),
        "signal_ring_events": sorted(
            {e for e in ring_events if isinstance(e, str)}
        ),
    }
    if "slo_breach" not in ring_events:
        return fail(
            "flight.json signal ring carries no slo_breach: "
            f"{ring_events[-10:]}", [0],
        )
    result["ok"] = True
    print(_json.dumps(result))


def _run_stream(args, result, tmp, procs, logs, victim, cmds, envs,
                port0, t0) -> None:
    """Continuous-training soak (`--chaos stream`, stream/driver.py): N
    ranks stream their shards in segments under an injected ingest stall
    (stream_stall fault) plus a mid-stream SIGTERM on one rank. Contract:
    (a) the whole fleet preempts cooperatively (rc 75 everywhere — the
    stall is absorbed, never a crash), (b) every rank's checkpoint carries
    its stream cursor (stream.json, integrity-covered), and (c) a full
    fleet relaunch with --resume replays each rank's in-progress segment
    from the cursor and runs the stream to completion (rc 0, manifest
    shutdown=clean, stream summary in the manifest end fields)."""
    import json as _json

    from word2vec_tpu.io.checkpoint import read_stream_cursor
    from word2vec_tpu.resilience.shutdown import EXIT_PREEMPTED

    result["chaos"] = "stream"
    result["victim_rank"] = victim

    def fail(msg, ranks=()):
        for p in procs:
            if p.poll() is None:
                p.kill()
        result["error"] = msg
        result["log_tails"] = [_tail(logs, r) for r in ranks]
        print(_json.dumps(result))

    deadline = time.time() + args.timeout
    for p in procs:
        try:
            p.wait(timeout=max(1.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            return fail(f"stream drill hang (> {args.timeout:.0f}s)",
                        range(len(procs)))
    result["preempt_wall_s"] = round(time.perf_counter() - t0, 1)
    result["rcs"] = [p.returncode for p in procs]
    if any(rc != EXIT_PREEMPTED for rc in result["rcs"]):
        return fail(
            f"expected every rank to exit {EXIT_PREEMPTED} (cooperative "
            f"mid-stream preemption), got {result['rcs']}",
            range(len(procs)),
        )
    doc = read_stream_cursor(os.path.join(tmp, "ck_shared"))
    if doc is None:
        return fail("shared checkpoint carries no stream.json cursor", [0])
    result["cursors"] = {
        "segment": doc.get("segment"), "shard": doc.get("shard"),
        "offset": doc.get("offset"),
        "global_steps": doc.get("global_steps"),
    }

    # --- resume leg: fresh fleet, fresh coordinator port, no faults ------
    port = free_port()
    t1 = time.perf_counter()
    procs2 = []
    for r, (cmd, env) in enumerate(zip(cmds, envs)):
        cmd2 = list(cmd)
        if "--faults" in cmd2:
            i = cmd2.index("--faults")
            del cmd2[i:i + 2]
        cmd2 += ["--resume", "ck_shared"]
        env2 = {**env, "W2V_COORDINATOR": f"127.0.0.1:{port}"}
        log = open(os.path.join(tmp, f"rank{r}.resume.log"), "w+")
        logs.append(log)
        procs2.append(subprocess.Popen(
            cmd2, cwd=tmp, env=env2,
            stdout=log, stderr=subprocess.STDOUT, text=True,
        ))
    deadline = time.time() + args.timeout
    for p in procs2:
        try:
            p.wait(timeout=max(1.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs2:
                q.kill()
            return fail(f"resume leg hang (> {args.timeout:.0f}s)",
                        range(len(procs), len(procs) + len(procs2)))
    result["resume_wall_s"] = round(time.perf_counter() - t1, 1)
    result["resume_rcs"] = [p.returncode for p in procs2]
    if any(result["resume_rcs"]):
        return fail(
            f"resume leg rcs={result['resume_rcs']}, want all 0",
            range(len(procs), len(procs) + len(procs2)),
        )
    man = _manifest(tmp, 0)
    result["resume_shutdown"] = man.get("shutdown")
    result["stream_summary"] = man.get("stream")
    if man.get("shutdown") != "clean":
        return fail(
            f"rank-0 manifest shutdown={man.get('shutdown')!r}, want "
            "'clean'", [len(procs)],
        )
    if not isinstance(man.get("stream"), dict) or not (
        man["stream"].get("segments", 0) >= 1
    ):
        return fail(
            f"rank-0 manifest stream summary missing/empty: "
            f"{man.get('stream')!r}", [len(procs)],
        )
    result["ok"] = True
    print(_json.dumps(result))


def _manifest(tmp, rank=0, mdir=None):
    try:
        with open(os.path.join(tmp, mdir or f"m{rank}", "manifest.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _tail(logs, r, n=12):
    logs[r].seek(0)
    return logs[r].read().strip().splitlines()[-n:]


def _run_elastic(args, result, tmp, procs, logs, victim, cmds, envs,
                 dp, t0) -> None:
    """Kill-one-of-N, elastic contract: survivors REMESH and CONTINUE
    (rc=0, never 75/76); shrink+grow additionally relaunches the victim and
    asserts sync-boundary readmission. Emits one JSON line with the
    per-phase walls, the external throughput curve sampled from the shared
    checkpoint, and (shrink mode) the byte-parity verdict against a fresh
    N-1 fleet resumed from the same generation snapshot."""
    import numpy as np

    result["chaos"] = args.chaos
    result["elastic_mode"] = args.elastic_mode
    result["victim_rank"] = victim
    result["kill_at_step"] = args.kill_at
    result["step_deadline_s"] = args.step_deadline
    result["sync_deadline_s"] = args.sync_deadline
    result["compile_cache"] = bool(args.compile_cache)
    # manifests are primary-gated: after the kill the new primary is the
    # lowest SURVIVING rank (old rank 1 when rank 0 is the victim — the
    # rank-0-kill drill's elected rendezvous host)
    mrank = min(r for r in range(len(procs)) if r != victim)

    def fail(msg, tails=()):
        for p in procs:
            if p.poll() is None:
                p.kill()
        result["error"] = msg
        if tails:
            result["log_tails"] = [_tail(logs, r) for r in tails]
        print(json.dumps(result))

    curve = []

    def sample_curve():
        """(t, step, words_done) from the SHARED checkpoint — an external
        observer's view of fleet progress, immune to the exec that
        separates generations (both renames in the rotation are atomic, so
        a read sees a complete dir or nothing)."""
        try:
            with np.load(os.path.join(tmp, "ck_shared", "state.npz")) as z:
                row = {
                    "t_s": round(time.perf_counter() - t0, 2),
                    "step": int(z["__step"]),
                    "words_done": int(z["__words_done"]),
                }
        except Exception:  # noqa: BLE001 — mid-rotation gap or no ckpt yet
            return
        if not curve or curve[-1]["step"] != row["step"]:
            curve.append(row)

    def wait_for(pred, budget, what):
        deadline = time.time() + budget
        while time.time() < deadline:
            sample_curve()
            if pred():
                return True
            # a survivor exiting is an immediate verdict, not a timeout
            for r, p in enumerate(procs):
                if r != victim and p.poll() is not None and p.returncode != 0:
                    return False
            time.sleep(0.4)
        return False

    # ---- phase 1: the victim dies at the pinned boundary ----------------
    hard = time.time() + args.timeout
    while procs[victim].poll() is None and time.time() < hard:
        sample_curve()
        time.sleep(0.2)
    if procs[victim].poll() is None:
        return fail(f"victim never died within {args.timeout:.0f}s", [victim])
    t_kill = time.perf_counter() - t0
    result["victim_rc"] = procs[victim].returncode
    if procs[victim].returncode != -9:
        return fail(
            f"victim exited rc={procs[victim].returncode}, expected "
            "SIGKILL(-9)", [victim],
        )
    result["t_kill_s"] = round(t_kill, 1)

    # ---- phase 2: survivors shrink-remesh to N-1 and keep training ------
    # budget: detection (~sync deadline) + the rendezvous join window
    # (2.5x sync deadline) + exec + jax.distributed re-init + recompile
    shrink_budget = 4.0 * args.sync_deadline + 120.0
    if not wait_for(
        lambda: _manifest(tmp, mrank).get("elastic_generation", 0) >= 1,
        shrink_budget, "shrink",
    ):
        survivors = [r for r in range(len(procs)) if r != victim]
        rcs = {str(r): procs[r].poll() for r in survivors}
        return fail(
            f"no generation-1 manifest within {shrink_budget:.0f}s of the "
            f"kill (survivor rcs so far: {rcs}) — survivors aborted or "
            "hung instead of remeshing", survivors,
        )
    man1 = _manifest(tmp, mrank)
    t_shrink = time.perf_counter() - t0
    result["shrink_detect_to_resume_s"] = round(t_shrink - t_kill, 1)
    result["gen1_world"] = (man1.get("mesh_events") or [{}])[-1].get("world")
    snap1 = os.path.join(tmp, "ck_shared.elastic_g1")
    result["gen1_snapshot"] = os.path.isdir(snap1)
    result["gen1_compile_cache"] = (man1.get("compile_cache") or None)
    if victim == 0:
        # rank-0 kill: the rendezvous died with its host, so generation 1
        # can only exist if the survivors RE-ELECTED it — assert the
        # election event landed in the manifest's mesh_events and that the
        # deciding rendezvous moved off the original coordinator address
        events = man1.get("mesh_events") or []
        elections = [e for e in events
                     if e.get("event") == "rendezvous_election"]
        result["election"] = elections[-1] if elections else None
        if not elections:
            return fail(
                "rank-0 kill formed generation 1 WITHOUT a recorded "
                f"rendezvous election (mesh_events: {events})",
                [r for r in range(len(procs)) if r != victim],
            )
        gen1 = [e for e in events if e.get("gen") == 1
                and e.get("event") == "generation_start"]
        result["gen1_rendezvous"] = (
            gen1[-1].get("rendezvous") if gen1 else None
        )
        result["gen1_trigger"] = gen1[-1].get("trigger") if gen1 else None

    # ---- phase 3 (shrink+grow): relaunch the victim, expect readmission -
    if args.elastic_mode == "shrink+grow":
        relaunch_cmd = [t for t in cmds[victim]]
        # strip the fault: a relaunched host re-killing itself would loop
        i = relaunch_cmd.index("--faults")
        del relaunch_cmd[i:i + 2]
        logs[victim].write("\n--- relaunched for rejoin ---\n")
        procs[victim] = subprocess.Popen(
            relaunch_cmd, cwd=tmp, env=envs[victim],
            stdout=logs[victim], stderr=subprocess.STDOUT, text=True,
        )
        grow_budget = 4.0 * args.sync_deadline + 150.0
        if not wait_for(
            lambda: _manifest(tmp, mrank).get("elastic_generation", 0) >= 2,
            grow_budget, "grow",
        ):
            return fail(
                f"no generation-2 manifest within {grow_budget:.0f}s of the "
                "relaunch — the rejoiner was not admitted",
                list(range(len(procs))),
            )
        t_grow = time.perf_counter() - t0
        result["grow_relaunch_to_resume_s"] = round(t_grow - t_shrink, 1)
        events = _manifest(tmp, mrank).get("mesh_events") or []
        gen2 = [e for e in events if e.get("gen") == 2
                and e.get("event") == "generation_start"]
        result["gen2_world"] = gen2[-1].get("world") if gen2 else None
        if result["gen2_world"] != args.procs:
            return fail(
                f"generation 2 formed at world {result['gen2_world']}, "
                f"expected {args.procs}", list(range(len(procs))),
            )

    # ---- completion: every LIVE process ends rc=0 (no 75/76 on this
    # path); in plain shrink mode the victim stays dead (-9) by design ----
    live = [r for r in range(len(procs))
            if args.elastic_mode == "shrink+grow" or r != victim]
    deadline = time.time() + args.timeout
    while time.time() < deadline:
        sample_curve()
        if all(procs[r].poll() is not None for r in live):
            break
        time.sleep(0.3)
    still = [r for r in live if procs[r].poll() is None]
    if still:
        return fail(f"ranks {still} still running at the drill timeout",
                    still)
    result["rcs"] = [p.returncode for p in procs]
    bad = [r for r in live if procs[r].returncode != 0]
    if bad:
        return fail(f"ranks {bad} exited nonzero on the elastic path "
                    f"(rcs={result['rcs']})", bad)
    result["wall_s"] = round(time.perf_counter() - t0, 1)

    # ---- blackout wall: kill -> first POST-KILL step progress ----------
    # the full recovery blackout an external observer sees (detection +
    # rendezvous + exec + jax re-init + COMPILE + the steps to the first
    # post-resume checkpoint rotation). The warm-restart compile cache
    # attacks the compile term: rerunning this drill with the same
    # --compile-cache dir banks the warm wall next to the cold one.
    pre_rows = [c for c in curve if c["t_s"] <= t_kill]
    step_at_kill = pre_rows[-1]["step"] if pre_rows else 0
    first_post = next(
        (c for c in curve
         if c["t_s"] > t_kill and c["step"] > step_at_kill), None,
    )
    result["blackout_to_first_progress_s"] = (
        round(first_post["t_s"] - t_kill, 1) if first_post else None
    )

    # ---- throughput curve: pre-kill vs post-remesh slopes ---------------
    # words_done is rank 0's LOCAL count (constant words per global step),
    # so the slope is proportional to the global step rate; fleet
    # throughput is slope x world. Recovery contract: the post-shrink fleet
    # rate should approach (N-1)/N of pre-kill — i.e. the per-host step
    # rate must not collapse (blackout excluded: slopes are measured
    # between checkpoint samples within one generation).
    def slope(rows):
        rates = []
        for a, b in zip(rows, rows[1:]):
            dt = b["t_s"] - a["t_s"]
            if dt > 0 and b["words_done"] > a["words_done"]:
                rates.append((b["words_done"] - a["words_done"]) / dt)
        return float(np.median(rates)) if rates else None
    pre = slope([c for c in curve if c["t_s"] <= t_kill])
    post_rows = [c for c in curve if c["t_s"] >= t_shrink]
    post = slope(post_rows)
    result["curve"] = curve
    result["words_per_s_rank0_prekill"] = round(pre, 1) if pre else None
    result["words_per_s_rank0_postshrink"] = round(post, 1) if post else None
    n = args.procs
    if pre and post:
        # fleet-level recovery ratio vs the (N-1)/N ideal
        result["fleet_recovery_ratio"] = round(
            (post * (n - 1)) / (pre * n), 3
        )
        result["fleet_recovery_target"] = round((n - 1) / n, 3)
        # loose CPU-noise bound; the banked JSON carries the exact ratio
        if post < 0.4 * pre:
            return fail(
                f"post-shrink step rate collapsed: {post:.0f} vs "
                f"{pre:.0f} words/s (rank-0 local)"
            )

    # ---- parity (shrink mode): fresh N-1 fleet from the same snapshot ---
    if args.elastic_mode == "shrink" and result["gen1_snapshot"]:
        ok, detail = _parity_reference(args, tmp, victim, dp)
        result["parity"] = detail
        if not ok:
            result["error"] = "byte-parity vs fresh N-1 resume FAILED"
            print(json.dumps(result))
            return

    result["ok"] = True
    print(json.dumps(result))


def _parity_reference(args, tmp, victim, dp):
    """Run a FRESH (N-1)-process fleet resumed from the generation-1
    snapshot on the survivors' shards and byte-compare its final vectors
    with the elastic run's: elastic continuation must be indistinguishable
    from a clean shrunken resume."""
    import filecmp

    survivors = [r for r in range(args.procs) if r != victim]
    world = len(survivors)
    new_dp = dp * world // args.procs
    port = free_port()
    eport = free_port()
    procs = []
    logs = []
    for i, r in enumerate(survivors):
        env = {
            **os.environ,
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "XLA_FLAGS": (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count="
                  f"{args.devices_per_proc}"
            ).strip(),
            "W2V_COORDINATOR": f"127.0.0.1:{port}",
            "W2V_NUM_PROCS": str(world),
            "W2V_PROC_ID": str(i),
            "W2V_ELASTIC_COORD": f"127.0.0.1:{eport}",
        }
        extra = [
            "--multihost", "--sync-mode", args.sync_mode,
            "--batch-rows", "8", "--dp-sync-every", "4", "--chunk-steps", "1",
            "--step-deadline", str(args.step_deadline),
            "--sync-deadline", str(args.sync_deadline),
            "--metrics-dir", f"mref{i}",
            "--elastic", args.elastic_mode,
            "--checkpoint-dir", "ck_ref", "--checkpoint-every", "5",
            "--checkpoint-keep", "2", "--quality-probe-every", "0",
            "--resume", "ck_shared.elastic_g1",
        ]
        log = open(os.path.join(tmp, f"ref{i}.log"), "w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            cli_cmd(f"shard{r}", "vocab.txt", "vec_ref.txt", new_dp,
                    args.tp, args.iters, tuple(extra),
                    method=args.train_method, dense_top=args.hs_dense_top),
            cwd=tmp, env=env, stdout=log, stderr=subprocess.STDOUT,
            text=True,
        ))
    deadline = time.time() + args.timeout
    for p in procs:
        try:
            p.wait(timeout=max(1.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return False, {"error": "reference fleet hung"}
    rcs = [p.returncode for p in procs]
    if any(rcs):
        tails = []
        for log in logs:
            log.seek(0)
            tails.append(log.read().strip().splitlines()[-8:])
        return False, {"error": f"reference rcs={rcs}", "log_tails": tails}
    same = filecmp.cmp(
        os.path.join(tmp, "vec_mp.txt"),
        os.path.join(tmp, "vec_ref.txt"),
        shallow=False,
    )
    return same, {"byte_identical": same, "reference_rcs": rcs,
                  "reference_world": world, "reference_dp": new_dp}


def _run_policy(args, result, tmp, procs, logs, straggler, t0) -> None:
    """Policy-driven autoscale drill (ISSUE 13 acceptance): ZERO failures
    injected — a stall stretch makes one rank a straggler, the
    --elastic-policy throughput rule drives a shrink that evicts it
    (trigger=policy), the evicted host parks as a rejoiner, and the
    policy's recovery rule opens the grow gate so it is readmitted
    (trigger=policy). Asserts exactly one shrink + one grow (hysteresis:
    no remesh oscillation), every process rc=0, and no failure-triggered
    remesh anywhere."""
    mdir = "mpol"

    def fail(msg, ranks=()):
        for p in procs:
            if p.poll() is None:
                p.kill()
        result["error"] = msg
        if ranks:
            result["log_tails"] = [_tail(logs, r) for r in ranks]
        print(json.dumps(result))

    result["chaos"] = "policy"
    result["straggler_rank"] = straggler
    result["policy"] = args.policy_spec

    def gen() -> int:
        return _manifest(tmp, mdir=mdir).get("elastic_generation", 0)

    def wait_for(pred, budget, what):
        deadline = time.time() + budget
        while time.time() < deadline:
            if pred():
                return True
            for r, p in enumerate(procs):
                if p.poll() is not None and p.returncode != 0:
                    result[f"early_exit_rank{r}"] = p.returncode
                    return False
            time.sleep(0.4)
        return False

    budget = 240.0
    if not wait_for(lambda: gen() >= 1, budget, "policy shrink"):
        return fail(
            f"no policy-shrink generation within {budget:.0f}s (gen "
            f"{gen()}) — the policy never actuated", range(len(procs)),
        )
    t_shrink = time.perf_counter() - t0
    result["policy_shrink_at_s"] = round(t_shrink, 1)
    if not wait_for(lambda: gen() >= 2, budget, "policy grow"):
        return fail(
            f"no policy-grow generation within {budget:.0f}s of the "
            f"shrink (gen {gen()}) — the evicted host was not readmitted",
            range(len(procs)),
        )
    result["policy_grow_at_s"] = round(time.perf_counter() - t0, 1)
    deadline = time.time() + args.timeout
    while time.time() < deadline:
        if all(p.poll() is not None for p in procs):
            break
        time.sleep(0.3)
    still = [r for r, p in enumerate(procs) if p.poll() is None]
    if still:
        return fail(f"ranks {still} still running at the drill timeout",
                    still)
    result["rcs"] = [p.returncode for p in procs]
    result["wall_s"] = round(time.perf_counter() - t0, 1)
    if any(result["rcs"]):
        return fail(
            f"zero-failure policy drill must end rc=0 everywhere, got "
            f"{result['rcs']}",
            [r for r, rc in enumerate(result["rcs"]) if rc],
        )
    man = _manifest(tmp, mdir=mdir)
    events = man.get("mesh_events") or []
    remeshes = [e for e in events if e.get("event") == "remesh"]
    result["mesh_events"] = [
        {k: e.get(k) for k in ("event", "gen", "kind", "trigger", "world",
                               "to_world", "victim")}
        for e in events
    ]
    failure = [e for e in remeshes if e.get("trigger") == "failure"]
    if failure:
        return fail(f"failure-triggered remesh in a ZERO-failure drill: "
                    f"{failure}", [0])
    shrinks = [e for e in remeshes if e.get("kind") == "policy_shrink"]
    grows = [e for e in remeshes if e.get("kind") == "grow"]
    if len(shrinks) != 1 or shrinks[0].get("trigger") != "policy":
        return fail(f"expected exactly ONE policy shrink, got {shrinks}",
                    [0])
    if shrinks[0].get("victim") != straggler:
        return fail(
            f"policy shrink evicted rank {shrinks[0].get('victim')}, "
            f"expected the injected straggler {straggler}", [0],
        )
    if len(grows) != 1 or grows[0].get("trigger") != "policy":
        return fail(f"expected exactly ONE policy grow, got {grows}", [0])
    if len(remeshes) != 2 or man.get("elastic_generation") != 2:
        return fail(
            f"remesh oscillation: {len(remeshes)} remeshes, final gen "
            f"{man.get('elastic_generation')} (hysteresis must pin "
            "exactly shrink->grow)", [0],
        )
    gen2 = [e for e in events if e.get("event") == "generation_start"
            and e.get("gen") == 2]
    result["final_world"] = gen2[-1].get("world") if gen2 else None
    if result["final_world"] != args.procs:
        return fail(
            f"final world {result['final_world']} != launch world "
            f"{args.procs}", [0],
        )
    result["ok"] = True
    print(json.dumps(result))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--tokens", type=int, default=200_000)
    ap.add_argument("--iters", type=int, default=3,
                    help="epochs; at dp=8 the per-replica sequential-update "
                    "budget is 1/8 of the token stream, so the margin gate "
                    "needs tokens*iters sized for the dp width")
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--sync-mode", choices=["mean", "delta"], default="mean")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel width WITHIN each process's "
                    "devices (the data axis is the only one that spans "
                    "processes; parallel/multihost.py topology policy)")
    ap.add_argument("--train-method", choices=["ns", "hs"], default="ns",
                    help="objective for both runs (hs exercises the "
                    "distributed backend on the second objective)")
    ap.add_argument("--hs-dense-top", type=int, default=0,
                    help="two-tier hs dense tier (config.hs_dense_top)")
    ap.add_argument("--chaos", metavar="SPEC", default="",
                    help="kill-one-of-N drill: deliver SPEC (e.g. "
                    "'peer_dead@8') to --chaos-rank only, run every rank "
                    "with the step/sync deadlines, and assert the "
                    "survivors exit within them instead of hanging; the "
                    "special value 'elastic' runs the elastic shrink/grow "
                    "drill instead (survivors must remesh and CONTINUE); "
                    "'rank0' runs the elastic drill with the RENDEZVOUS "
                    "HOST as the victim (rank0_dead fault): survivors "
                    "must re-elect the rendezvous onto the lowest "
                    "surviving rank, shrink to N-1, and byte-match a "
                    "fresh N-1 resume — the rank-0-survival acceptance; "
                    "'policy' runs the ZERO-failure autoscale drill "
                    "(resilience/policy.py): a stall stretch makes "
                    "--chaos-rank a straggler, the --policy-spec rules "
                    "drive a trigger=policy shrink evicting it and a "
                    "later trigger=policy grow readmitting it, with "
                    "hysteresis pinned (exactly one of each); "
                    "the special value 'signals' runs the fleet signal-"
                    "plane drill (obs/signals.py): repeated stalls slow "
                    "--chaos-rank, every rank publishes windowed signal "
                    "rows into ONE shared metrics dir, and the drill "
                    "asserts fleet.json names the straggler host, the "
                    "--slo throughput rule escalates warn->breach, and "
                    "the SloEvent lands in rank 0's flight.json; "
                    "the special value 'stream' runs the continuous-"
                    "training soak (stream/driver.py): every rank streams "
                    "its shard in segments, --chaos-rank gets an injected "
                    "stream_stall plus a mid-stream SIGTERM, the whole "
                    "fleet must preempt rc 75 with stream cursors in "
                    "every checkpoint, and a full --resume relaunch must "
                    "replay to clean completion (rc 0)")
    ap.add_argument("--policy-spec", metavar="RULES",
                    default="throughput_wps<0.55*baseline:for=2:baseline=2"
                            ":act=shrink,"
                            "throughput_wps>0.7*baseline:for=2:baseline=2"
                            ":act=grow,cooldown=3",
                    help="--chaos policy: the --elastic-policy rules "
                    "forwarded to every rank")
    ap.add_argument("--compile-cache", metavar="DIR", default="",
                    help="elastic drills: forward --compile-cache DIR to "
                    "every rank (warm-restart compile cache; pass the "
                    "SAME absolute dir to a second drill run to measure "
                    "the warm blackout against the cold one)")
    ap.add_argument("--elastic-mode", choices=["shrink", "shrink+grow"],
                    default="shrink+grow",
                    help="--chaos elastic: shrink runs the kill->remesh leg "
                    "plus the byte-parity check against a fresh N-1 resume; "
                    "shrink+grow additionally relaunches the victim and "
                    "asserts sync-boundary readmission at world N")
    ap.add_argument("--kill-at", type=int, default=6,
                    help="--chaos elastic: step boundary of the victim's "
                    "SIGKILL (after the first checkpoint at step 5, so a "
                    "verified resume point exists)")
    ap.add_argument("--chaos-rank", type=int, default=-1,
                    help="rank receiving the chaos fault (-1 = the LAST "
                    "rank, keeping process 0 — the jax.distributed "
                    "coordinator — alive so the drill tests collective "
                    "hang detection, not coordinator loss)")
    ap.add_argument("--step-deadline", type=float, default=8.0,
                    help="chaos mode: --step-deadline forwarded to every rank")
    ap.add_argument("--sync-deadline", type=float, default=8.0,
                    help="chaos mode: --sync-deadline forwarded to every rank")
    args = ap.parse_args()

    from word2vec_tpu.utils.synthetic import topic_corpus, topic_similarity_pairs

    tokens, topic_of = topic_corpus(n_tokens=args.tokens, seed=0)
    pairs = topic_similarity_pairs(topic_of, seed=1)
    dp = args.procs * args.devices_per_proc // args.tp

    result = {
        "config": f"sg+{args.train_method}"
        f"{f'-dense{args.hs_dense_top}' if args.hs_dense_top else ''} "
        f"dim=64 iters={args.iters} dp={dp} tp={args.tp} "
        f"over {args.procs} processes x {args.devices_per_proc} virtual "
        f"cpu devices, sync={args.sync_mode}",
        "corpus": f"topic-synthetic-{args.tokens} tokens, "
        f"{args.procs} round-robin shards",
    }

    with tempfile.TemporaryDirectory() as tmp:
        # full corpus + per-process shards (round-robin over the reference's
        # 1000-token chunking unit so shard sizes stay balanced)
        chunks = [tokens[i:i + 1000] for i in range(0, len(tokens), 1000)]
        with open(os.path.join(tmp, "full"), "w") as f:
            f.write(" ".join(tokens))
        for r in range(args.procs):
            with open(os.path.join(tmp, f"shard{r}"), "w") as f:
                f.write(" ".join(
                    w for c in chunks[r::args.procs] for w in c
                ))

        # one shared vocabulary: every process must agree on the word->row
        # mapping, exactly as a real multi-host run ships one vocab file
        from word2vec_tpu.data.vocab import Vocab

        Vocab.build([c for c in chunks], min_count=5).save(
            os.path.join(tmp, "vocab.txt")
        )

        env_base = {
            **os.environ,
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "XLA_FLAGS": (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={args.devices_per_proc}"
            ).strip(),
        }

        # --- multi-process run -------------------------------------------
        rank0_drill = args.chaos == "rank0"
        policy_drill = args.chaos == "policy"
        elastic = args.chaos == "elastic" or rank0_drill
        signals_drill = args.chaos == "signals"
        stream_drill = args.chaos == "stream"
        stream_seg = 0
        if stream_drill:
            # equal-length contiguous shards: the per-segment steps/epoch
            # is a cross-process agreement, so every rank must see the SAME
            # segment structure — round-robin shards can differ by a chunk
            # and split into different segment counts (collective mismatch)
            per = len(tokens) // args.procs
            for r in range(args.procs):
                with open(os.path.join(tmp, f"shard{r}"), "w") as f:
                    f.write(" ".join(tokens[r * per:(r + 1) * per]))
            stream_seg = max(2_000, per // 3)
        if rank0_drill:
            # the rendezvous host is the victim; it stays dead (shrink
            # mode) and the drill byte-checks the elected continuation
            args.elastic_mode = "shrink"
        victim = None
        if args.chaos:
            victim = (
                args.chaos_rank if args.chaos_rank >= 0
                else (0 if rank0_drill else args.procs - 1)
            )
        port = free_port()
        elastic_port = free_port() if elastic or policy_drill else None
        # per-rank standby rendezvous table: explicit free ports (the
        # default port+rank derivation risks collisions on a busy host)
        peer_addrs = None
        if elastic_port is not None:
            peer_addrs = [f"127.0.0.1:{elastic_port}"] + [
                f"127.0.0.1:{free_port()}" for _ in range(args.procs - 1)
            ]
        t0 = time.perf_counter()
        procs = []
        logs = []
        cmds = []
        envs = []
        for r in range(args.procs):
            env = {
                **env_base,
                "W2V_COORDINATOR": f"127.0.0.1:{port}",
                "W2V_NUM_PROCS": str(args.procs),
                "W2V_PROC_ID": str(r),
            }
            if peer_addrs is not None:
                env["W2V_ELASTIC_COORD"] = peer_addrs[0]
                env["W2V_ELASTIC_PEERS"] = ",".join(peer_addrs)
            extra = ["--multihost", "--sync-mode", args.sync_mode]
            if args.chaos:
                extra += [
                    # small pinned geometry: auto sizing on this corpus gives
                    # ~1 dispatch per epoch, so a step-pinned fault would
                    # never fire and there would be no boundaries to beat
                    "--batch-rows", "8",
                    # tight sync cadence so the heartbeat/agree collectives
                    # (the bounded channel) actually run before the drill ends
                    "--dp-sync-every", "4",
                    # per-step boundaries: the watchdog's adaptive deadline
                    # needs steady beats, and the fault lands promptly
                    "--chunk-steps", "1",
                    "--step-deadline", str(args.step_deadline),
                    "--sync-deadline", str(args.sync_deadline),
                    # signals/policy drills: ONE shared metrics dir — each
                    # rank's signals_p<r>.jsonl is a distinct file (the
                    # PR 6 trace_p<i>.json discipline) and rank 0 merges
                    # them (the policy's straggler attribution input)
                    "--metrics-dir",
                    "msig" if signals_drill
                    else ("mpol" if policy_drill else f"m{r}"),
                ]
                if stream_drill:
                    extra += [
                        "--corpus-mode", "streaming",
                        "--segment-tokens", str(stream_seg),
                        # SHARED checkpoint dir: saves are primary-gated
                        # (rank 0 writes for the fleet), and the equalized
                        # shards keep every rank's stream cursor identical,
                        # so one cursor resumes the whole fleet
                        "--checkpoint-dir", "ck_shared",
                        "--checkpoint-every", "4",
                        "--quality-probe-every", "0",
                    ]
                    if r == victim:
                        # ingest hiccup + mid-stream preemption: the stall
                        # must be absorbed as batcher wait; the SIGTERM
                        # preempts the whole fleet cooperatively (rc 75)
                        extra += ["--faults",
                                  "stream_stall@1:secs=0.4,sigterm@8"]
                elif signals_drill:
                    extra += [
                        "--signal-window", "5",
                        # baseline from the first 2 clean windows; the
                        # injected stall stretch must drop throughput below
                        # 60% of it for 2 consecutive windows -> breach
                        "--slo",
                        "throughput_wps<0.6*baseline:for=2:baseline=2",
                        "--checkpoint-dir", f"ck{r}",
                        "--checkpoint-every", "10",
                    ]
                    if r == victim:
                        # the injected straggler: a 0.25s stall at every
                        # boundary in steps 10..26 — long enough to span
                        # several windows, slow enough to never trip the
                        # step watchdog
                        extra += ["--faults", ",".join(
                            f"stall@{s}:secs=0.25" for s in range(10, 27)
                        )]
                    elif r == 0:
                        # the drill's flight trigger: a SIGTERM fault well
                        # after the breach preempts the fleet cooperatively
                        # (rc 75 everywhere) and rank 0 dumps flight.json
                        # with the SloEvents on its signal ring
                        extra += ["--faults", "sigterm@30"]
                if policy_drill:
                    extra += [
                        "--elastic", "shrink+grow",
                        "--elastic-policy", args.policy_spec,
                        "--signal-window", "5",
                        "--checkpoint-dir", "ck_shared",
                        "--checkpoint-every", "5",
                        "--checkpoint-keep", "2",
                        "--quality-probe-every", "0",
                    ]
                    if r == victim:
                        # the injected straggler (NOT a failure): a 0.5s
                        # stall at every boundary from step 12 on — the
                        # fleet's lockstep throughput drops below the
                        # policy's 0.55x baseline for consecutive windows
                        # and the host_overhead attribution names this
                        # rank; the stalls are stripped at the eviction
                        # exec so the rejoiner comes back healthy
                        extra += ["--faults", ",".join(
                            f"stall@{s}:secs=0.5" for s in range(12, 61)
                        )]
                elif elastic:
                    extra += [
                        "--elastic", args.elastic_mode,
                        # SHARED checkpoint dir (the elastic contract: all
                        # hosts must read the same integrity chain), tight
                        # cadence so a verified resume point predates the
                        # kill, keep>=2 so rotation never leaves the chain
                        # empty mid-write
                        "--checkpoint-dir", "ck_shared",
                        "--checkpoint-every", "5",
                        "--checkpoint-keep", "2",
                        # probe cadence is a sync boundary; pinned off so the
                        # byte-parity reference run trivially matches it
                        "--quality-probe-every", "0",
                    ]
                elif not stream_drill:
                    extra += [
                        "--checkpoint-dir", f"ck{r}",
                        "--checkpoint-every", "5",
                    ]
                if elastic and args.compile_cache:
                    extra += [
                        "--compile-cache", os.path.abspath(args.compile_cache)
                    ]
                if (
                    r == victim and not signals_drill
                    and not policy_drill and not stream_drill
                ):
                    kind = (
                        "rank0_dead" if rank0_drill else
                        "peer_rejoin" if args.elastic_mode == "shrink+grow"
                        else "peer_dead"
                    ) if elastic else None
                    extra += ["--faults",
                              f"{kind}@{args.kill_at}" if elastic
                              else args.chaos]
            # child output goes to FILES, not pipes: an undrained pipe fills
            # at ~64 KiB and deadlocks the child against our wait()
            log = open(os.path.join(tmp, f"rank{r}.log"), "w+")
            logs.append(log)
            cmd = cli_cmd(f"shard{r}", "vocab.txt", "vec_mp.txt", dp, args.tp,
                          args.iters, tuple(extra),
                          method=args.train_method,
                          dense_top=args.hs_dense_top)
            cmds.append(cmd)
            envs.append(env)
            procs.append(subprocess.Popen(
                cmd, cwd=tmp, env=env,
                stdout=log, stderr=subprocess.STDOUT, text=True,
            ))
        if elastic:
            _run_elastic(args, result, tmp, procs, logs, victim,
                         cmds, envs, dp, t0)
            return
        if policy_drill:
            _run_policy(args, result, tmp, procs, logs, victim, t0)
            return
        if signals_drill:
            _run_signals(args, result, tmp, procs, logs, victim, t0)
            return
        if stream_drill:
            _run_stream(args, result, tmp, procs, logs, victim,
                        cmds, envs, port, t0)
            return
        if args.chaos:
            _run_chaos(args, result, tmp, procs, logs, victim, t0)
            return
        deadline = time.time() + args.timeout
        rcs = []
        for p in procs:
            try:
                p.wait(timeout=max(1.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                print(json.dumps({**result, "error": "multiproc hang "
                                  f"(> {args.timeout:.0f}s)"}))
                return
            rcs.append(p.returncode)
        result["multiproc_wall_s"] = round(time.perf_counter() - t0, 1)
        if any(rcs):
            tails = []
            for log in logs:
                log.seek(0)
                tails.append(log.read().strip().splitlines()[-8:])
            print(json.dumps({**result, "error": f"multiproc rcs={rcs}",
                              "log_tails": tails}))
            return
        result["multiproc"] = eval_vectors(
            os.path.join(tmp, "vec_mp.txt"), pairs, topic_of
        )

        # --- identical single-process run --------------------------------
        env = {
            **env_base,
            "XLA_FLAGS": (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={dp * args.tp}"
            ).strip(),
        }
        sp = subprocess.run(
            cli_cmd("full", "vocab.txt", "vec_sp.txt", dp, args.tp,
                    args.iters, method=args.train_method,
                    dense_top=args.hs_dense_top),
            cwd=tmp, env=env, capture_output=True, text=True,
            timeout=args.timeout,
        )
        if sp.returncode != 0:
            print(json.dumps({**result, "error": "singleproc rc="
                              f"{sp.returncode}",
                              "stderr_tail": sp.stderr.splitlines()[-8:]}))
            return
        result["singleproc"] = eval_vectors(
            os.path.join(tmp, "vec_sp.txt"), pairs, topic_of
        )

    for k in ("spearman", "neighbor_purity@10", "cos_margin"):
        result[f"delta_{k}"] = round(
            result["multiproc"][k] - result["singleproc"][k], 4
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
