#!/bin/bash
# Round-12 TPU measurement queue — the fully-fused Pallas train step
# (ISSUE 12, band_backend='pallas_fused').
#
# The tunnel has been dead since round 5, so queues 5/7/8 coexist: this one
# is ordered so a SHORT window banks the decision this round actually made.
#
#   Tier 1 — the A/B trio that decides the tentpole at the banked 30.4x
#            config: unified/xla (the r7 chain) vs unified/pallas_oa (the
#            best predicted chain) vs unified/pallas_fused. The cost model
#            predicts the fused step ~11% over pallas_oa and ~36% over the
#            unified chain at the flagship shape (program-gap tail
#            collapses 9 -> 3 programs + the inter-op round-trips
#            disappear, minus ~1.6 ms of in-kernel DMA rows —
#            tune/cost_model.py PROGRAM_GAP_MS / DMA_SEC_PER_ROW;
#            sensitivity pinned by the r12 counterfactual-flip test).
#            CPU interpret evidence: benchmarks/COST_ATTRIB_r12.
#   Tier 2 — --trace step-span exports of fused vs chain so
#            `python -m word2vec_tpu.obs.tracediff` attributes the
#            dispatch/program-gap delta WITH SIGN from banked artifacts
#            (the PR 6 pattern; the fused step's whole claim lives in the
#            dispatch span delta).
#   Tier 3 — the fused planner-candidate stacks: pallas_fused x
#            {kp16, bf16sr, chunk-cap 96}, and an --autotune probe that
#            must be free to pick (or reject) the fused backend.
#
# Forwarding-audit markers (the r4 lesson): an item banks ONLY a record
# whose realized plan carries the requested band_backend/layout — bench.py's
# outer->inner re-exec once dropped a flag and banked the XLA path under a
# pallas label. The plan JSON carries band_backend before table_layout
# (TunePlan field order), and "platform" precedes "plan" in bench.py's
# record, so one basic-regex grep covers each marker.
#
# Usage: nohup bash benchmarks/tpu_queue8.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/TPU_R8
. benchmarks/tpu_queue_lib.sh

B='python bench.py --probe-retries 1'
TPU='"platform": "tpu"'
# realized-backend markers: "band_backend" rides inside the record's "plan"
UNI='"platform": "tpu".*"band_backend": "xla".*"table_layout": "unified"'
UNI_OA='"platform": "tpu".*"band_backend": "pallas_oa".*"table_layout": "unified"'
FUSED='"platform": "tpu".*"band_backend": "pallas_fused".*"table_layout": "unified"'
FUSED_KP16='"platform": "tpu".*"shared_negatives": 16.*"band_backend": "pallas_fused".*"table_layout": "unified"'
FUSED_BF16SR='"platform": "tpu".*"band_backend": "pallas_fused".*"table_layout": "unified".*"table_dtype": "bfloat16".*"stochastic_rounding": true'

# --- tier 1: the backend A/B that decides the tentpole ------------------------
run_item unified_xla          900 "$UNI"    $B --table-layout unified
run_item unified_pallas_oa    900 "$UNI_OA" $B --table-layout unified --band-backend pallas_oa
run_item unified_fused        900 "$FUSED"  $B --table-layout unified --band-backend pallas_fused

# --- tier 2: tracediff artifacts (fused dispatch-delta attribution) -----------
# diffing these attributes the program-gap collapse to the dispatch span
# with sign (obs/tracediff.py; the r12 test pins the sign convention):
run_item unified_xla_tracedump   900 "$UNI"   $B --table-layout unified --trace benchmarks/TPU_R8/trace_chain
run_item unified_fused_tracedump 900 "$FUSED" $B --table-layout unified --band-backend pallas_fused --trace benchmarks/TPU_R8/trace_fused

# --- tier 3: fused planner-candidate stacks -----------------------------------
# fused x KP width (the kp16 win was 100% dispatch — if the fused step
# already deleted the tail, the kp16 stack tells us what is left):
run_item fused_kp16           900 "$FUSED_KP16" $B --table-layout unified --band-backend pallas_fused --kp 16
# fused x bf16+SR (halved slab bytes compose with the in-kernel gathers):
run_item fused_bf16sr         900 "$FUSED_BF16SR" $B --table-layout unified --band-backend pallas_fused --table-dtype bfloat16 --sr 1
# fused x deeper scan megasteps (dispatch overhead amortization on top of
# the in-step program-gap collapse — the two tails are different):
run_item fused_c96            900 "$FUSED" $B --table-layout unified --band-backend pallas_fused --chunk-cap 96
# the planner's own verdict (probe mode persists the winner under the
# schema-3 key that now carries the configured band_backend):
run_item autotune_probe_fused 1800 "$TPU" $B --autotune probe --table-layout unified --band-backend pallas_fused

echo "$(date -u +%FT%TZ) QUEUE8 COMPLETE after $FAILED_PROBES failed probes total" >> "$LOG"
