#!/bin/bash
# Round-4 TPU measurement queue — idempotent AND auditable.
#
# Same banking discipline as tpu_queue3.sh (one JSON per item in
# benchmarks/TPU_R4/, items skip when banked, probe before every item), plus
# the round-3 verdict's auditability fixes: a "queue started" line at launch,
# a heartbeat line while the tunnel is down, and a flock single-instance
# guard. The shared machinery lives in tpu_queue_lib.sh; this file is just
# the round's item list. bench.py scans all benchmarks/TPU_R*/ dirs when
# attaching best_banked_tpu, so results banked here are picked up
# automatically.
#
# Usage: nohup bash benchmarks/tpu_queue4.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/TPU_R4
. benchmarks/tpu_queue_lib.sh

B='python bench.py --probe-retries 1'
TPU='"platform": "tpu"'

# --- phase 1: the lever sweep (VERDICT r3 item 1) ----------------------------
run_item default      900 "$TPU" $B
# the best-guess stacks right after the headline default, in case the live
# window is short: these items alone give the 50x shots + their baseline
run_item fused_kp32_c96       900 "$TPU" $B --fused 1 --kp 32 --chunk-cap 96
# (full_stack wedged >900s on its first attempt and the kill coincided
# with a tunnel outage; retried at the END of tpu_queue4b.sh with 1800s)
# the fused Pallas band kernel: the single most informative new item —
# measured early in case the live window is short
run_item pallas       900 "$TPU" $B --band-backend pallas
run_item b512         900 "$TPU" $B --batch-rows 512
run_item chunk96      900 "$TPU" $B --chunk-cap 96
run_item fused        900 "$TPU" $B --fused 1
run_item kp32         900 "$TPU" $B --kp 32
run_item rbg          900 "$TPU" $B --prng rbg
run_item slab_sorted  900 "$TPU" $B --slab-scatter 1

# Fresh step trace with round-4 defaults, hoisted ahead of the combos: with
# the tunnel surfacing in minutes-long windows, the trace is the one item
# that tells us WHERE the 11.4 ms step goes (pallas tied default on-chip,
# so the r2 cost model is stale) — it must not sit behind ~2 h of items.
run_trace /tmp/tr_r4

# BASELINE configs 2 & 3 + the w=10 shape (VERDICT r3 item 3), also hoisted:
# per-config coverage beats combo resolution if the tunnel dies early.
# vs the measured 672k / 426k / 87.4k reference baselines
# (benchmarks/reference_baselines.json)
run_item cbow_dim100  900 "$TPU" $B --model cbow --dim 100
run_item hs_dim200    900 "$TPU" $B --train-method hs --dim 200
run_item sg_w10       900 "$TPU" $B --window 10

run_item pallas_b512_c96      900 "$TPU" $B --band-backend pallas --batch-rows 512 --chunk-cap 96
# combos (each lever is independent machinery; measure the stack)
run_item fused_kp32           900 "$TPU" $B --fused 1 --kp 32
run_item fused_kp32_c96_rbg   900 "$TPU" $B --fused 1 --kp 32 --chunk-cap 96 --prng rbg
run_item fused_kp32_c96_b512  900 "$TPU" $B --fused 1 --kp 32 --chunk-cap 96 --batch-rows 512

# batch-scoped shared negatives (one dense matmul + KP-row update scatter;
# parity-validated at kp=256: delta_spearman 0.0, delta_margin +0.031)
run_item negbatch_kp256       900 "$TPU" $B --neg-scope batch --kp 256
run_item negbatch_kp256_fused_c96 900 "$TPU" $B --neg-scope batch --kp 256 --fused 1 --chunk-cap 96

# bf16 table storage + stochastic rounding
run_item bf16sr               900 "$TPU" $B --table-dtype bfloat16 --sr 1
run_item bf16sr_fused_kp32_c96 900 "$TPU" $B --table-dtype bfloat16 --sr 1 --fused 1 --kp 32 --chunk-cap 96

# --- phase 2: quality at scale on chip (VERDICT r3 item 5) -------------------
# marker is the platform field (cli --emit-device → quality_full JSON): a
# silent CPU fallback must not bank as an on-chip quality result
run_item quality_hs_dim300 2400 "$TPU" \
  python benchmarks/quality_full.py --tokens 4000000 --train-method hs --dim 300
run_item quality_sg_dim300 2400 "$TPU" \
  python benchmarks/quality_full.py --tokens 4000000
run_item quality_analogy_dim300 2400 "$TPU" \
  python benchmarks/quality_full.py --analogy --tokens 4000000

# --- phase 3: enwik9-shape scale run (VERDICT r3 item 4) ---------------------
run_item enwik9_100M 3600 "$TPU" $B --tokens 100000000 --window 10 --run-timeout 3000

echo "$(date -u +%FT%TZ) QUEUE COMPLETE after $FAILED_PROBES failed probes total" >> "$LOG"
