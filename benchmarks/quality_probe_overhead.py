#!/usr/bin/env python
"""Measure the in-training quality probe's overhead on the CPU drill shape.

The probe contract (obs/quality.py) is two-sided: non-probe steps cost one
integer compare (due() — same class as the watchdog's beat), and a firing
probe costs one device fetch of the tables plus host/engine scoring,
amortized over its cadence. This harness pins both as banked numbers
instead of hopes: it trains the same synthetic shape with no probe, with an
attached-but-never-firing probe (the machinery cost), and with the probe at
a production cadence (the amortized cost), alternating reps and taking
median walls; it also times due() itself against the run's own p50 step.

One JSON line to stdout (bank as benchmarks/QUALITY_PROBE_OVERHEAD_cpu.json):
    python benchmarks/quality_probe_overhead.py [--tokens 200000] [--reps 3]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=200_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch-rows", type=int, default=64)
    ap.add_argument("--every", type=int, default=5,
                    help="probe cadence of the firing-probe leg (the drill "
                    "shape runs ~18 steps, so 5 fires a few probes; the "
                    "CLI's production default of 100 amortizes ~20x "
                    "further)")
    args = ap.parse_args()

    import numpy as np

    import jax
    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import PackedCorpus
    from word2vec_tpu.obs.quality import ProbeSet, QualityProbe
    from word2vec_tpu.train import Trainer
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model="sg", train_method="ns", negative=5, word_dim=args.dim,
        window=5, batch_rows=args.batch_rows, max_sentence_len=192,
        min_count=1, iters=1, seed=0,
        chunk_steps=1,  # per-step boundaries: the worst case for due() count
    )
    vocab = zipf_vocab(71000, 17_000_000)
    flat = np.concatenate(zipf_corpus_ids(vocab, args.tokens, seed=0))
    ids = [flat[i:i + 1000] for i in range(0, len(flat), 1000)]
    corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
    trainer = Trainer(cfg, vocab, corpus)
    pset = ProbeSet.synthesize(vocab)  # zipf naming -> stats-only probe

    def timed_run(every):
        """every=None -> no probe; huge -> attached but idle; small ->
        firing at the production cadence."""
        probe = None
        if every is not None:
            probe = QualityProbe(vocab, pset, every=every,
                                 flight=trainer.flight)
        trainer.quality_probe = probe
        t0 = time.perf_counter()
        _, rep = trainer.train(state=trainer.init_state(), log_every=0)
        wall = time.perf_counter() - t0
        trainer.quality_probe = None
        return wall, rep, probe

    timed_run(None)  # warmup: compile out of the measurement
    base_walls, idle_walls, fire_walls = [], [], []
    steps = probes = 0
    for _ in range(args.reps):  # alternate to decorrelate host drift
        w, rep, _ = timed_run(None)
        base_walls.append(w)
        steps = rep.steps
        w, _, _ = timed_run(10**9)
        idle_walls.append(w)
        w, _, probe = timed_run(args.every)
        fire_walls.append(w)
        probes = probe.probes

    # due() microcost against the run's own p50 step time
    probe = QualityProbe(vocab, pset, every=10**9)
    trainer.quality_probe = probe
    _, rep = trainer.train(state=trainer.init_state(), log_every=0)
    step_ms = sorted(
        e["dur"] / 1e3 for e in trainer.flight.ring.events()
        if e.get("ph") == "X" and e["name"] == "step"
    )
    p50_step_ms = statistics.median(step_ms)
    trainer.quality_probe = None
    n = 100_000
    t0 = time.perf_counter()
    for i in range(n):
        probe.due(i)
    per_due_us = 1e6 * (time.perf_counter() - t0) / n

    base = statistics.median(base_walls)
    idle = statistics.median(idle_walls)
    fire = statistics.median(fire_walls)
    probe_spans = [
        e["dur"] / 1e3 for e in trainer.flight.ring.events()
        if e.get("ph") == "X" and e["name"] == "quality_probe"
    ]
    probe_ms = statistics.median(probe_spans) if probe_spans else None
    # THE contract number: one measured probe amortized over the CLI's
    # production cadence (100 steps) of this run's own p50 step — the
    # drill's wall A/B at a dense cadence is banked alongside but is
    # hostage to 1-core host noise at this wall length
    prod_every = 100
    amortized_pct = (
        100.0 * probe_ms / (prod_every * p50_step_ms)
        if probe_ms else None
    )
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": f"quality-probe overhead at production cadence "
                  f"({args.tokens // 1000}k zipf, {dev.platform})",
        "value": round(amortized_pct, 3) if amortized_pct else None,
        "unit": f"% wall at every={prod_every}",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "steps_per_run": steps,
        "probe_every": args.every,
        "probes_per_run": probes,
        "reps": args.reps,
        "base_wall_s": [round(w, 3) for w in base_walls],
        "idle_probe_wall_s": [round(w, 3) for w in idle_walls],
        "firing_probe_wall_s": [round(w, 3) for w in fire_walls],
        "median_base_s": round(base, 3),
        "median_idle_s": round(idle, 3),
        "median_firing_s": round(fire, 3),
        "idle_overhead_pct": round(100.0 * (idle - base) / base, 2),
        "firing_overhead_pct": round(100.0 * (fire - base) / base, 2),
        "p50_step_ms": round(p50_step_ms, 3),
        "due_cost_us": round(per_due_us, 3),
        "due_cost_pct_of_step": round(
            100.0 * per_due_us / (1e3 * p50_step_ms), 4
        ),
        "probe_span_ms": round(probe_ms, 3) if probe_ms else None,
        "amortized_pct_at_production_cadence": (
            round(amortized_pct, 3) if amortized_pct else None
        ),
    }))


if __name__ == "__main__":
    main()
