#!/usr/bin/env python
"""Measure the device-truth observability overhead on the CPU drill shape.

The contract (obs/devmem.py + obs/harvest.py + obs/profiler.py) is the same
standing one as trace/watchdog/signals/quality before it: the per-boundary
work is an integer compare (ledger cadence), a None-check pair (idle
profiler), and one set lookup (harvest capture latch) — zero device
dispatches on non-sample boundaries; the ledger SAMPLE is one host-side
client call per local device on its cadence, and the harvest's
lower+compile runs once, AFTER the measured loop. This harness pins the
<1% wall number the PR 5/6/9/11 way: train the same synthetic shape with
the full wiring attached (ledger at the default cadence, harvest capturing,
an idle profiler armed for SIGUSR2) and detached, order-fair alternating
reps, median wall; then time the per-boundary beats directly.

One JSON line to stdout (bank as benchmarks/DEVMEM_OVERHEAD_cpu.json):
    python benchmarks/devmem_overhead.py [--tokens 200000] [--reps 3]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=200_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch-rows", type=int, default=64)
    ap.add_argument("--sample-every", type=int, default=50)
    args = ap.parse_args()

    import numpy as np

    import jax
    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import PackedCorpus
    from word2vec_tpu.obs.devmem import MemoryLedger, table_row_bytes
    from word2vec_tpu.obs.harvest import CostHarvest
    from word2vec_tpu.obs.profiler import ProfilerCapture
    from word2vec_tpu.train import Trainer
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model="sg", train_method="ns", negative=5, word_dim=args.dim,
        window=5, batch_rows=args.batch_rows, max_sentence_len=192,
        min_count=1, iters=1, seed=0,
        chunk_steps=1,  # per-step boundaries: the worst case for beat count
    )
    vocab = zipf_vocab(71000, 17_000_000)
    flat = np.concatenate(zipf_corpus_ids(vocab, args.tokens, seed=0))
    ids = [flat[i:i + 1000] for i in range(0, len(flat), 1000)]
    corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
    trainer = Trainer(cfg, vocab, corpus)
    tmp = tempfile.mkdtemp(prefix="w2v_devmem_overhead_")

    def wire(on: bool):
        if on:
            trainer.devmem = MemoryLedger(
                sample_every=args.sample_every,
                flight=trainer.flight,
                row_bytes=table_row_bytes(cfg),
            )
            trainer.harvest = CostHarvest()
            trainer.profiler = ProfilerCapture(tmp)  # idle: never armed
        else:
            trainer.devmem = None
            trainer.harvest = None
            trainer.profiler = None

    def timed_run(wired: bool):
        wire(wired)
        t0 = time.perf_counter()
        _, rep = trainer.train(state=trainer.init_state(), log_every=0)
        wall = time.perf_counter() - t0
        if wired:
            # the harvest's one-time analysis runs after the loop in
            # production (cli/bench finalize there too) — include it in the
            # wired wall so the banked number is the WHOLE cost
            trainer.harvest.finalize()
        return time.perf_counter() - t0, wall, rep

    timed_run(True)  # warmup: compile out of the measurement
    base_walls, wired_walls, wired_loop_walls, steps, samples = [], [], [], 0, 0
    for i in range(args.reps):
        # ORDER-FAIR alternation (the signal_overhead.py discipline): the
        # second run of a back-to-back pair is systematically slower on
        # this host; flipping the order per rep cancels the bias
        for wired in ((False, True) if i % 2 == 0 else (True, False)):
            total, loop, rep = timed_run(wired)
            if wired:
                wired_walls.append(total)
                wired_loop_walls.append(loop)
                samples = (rep.device_memory or {}).get("samples", 0)
            else:
                base_walls.append(total)
                steps = rep.steps

    # per-boundary microcosts: the in-suite contract test enforces these
    # (the wall A/B straddles zero inside host noise on the shared bench
    # host, exactly like the signal plane's)
    _, _, rep = timed_run(False)
    step_durs_ms = sorted(
        e["dur"] / 1e3
        for e in trainer.flight.ring.events()
        if e.get("ph") == "X" and e["name"] == "step"
    )
    p50_step_ms = step_durs_ms[len(step_durs_ms) // 2]
    ledger = MemoryLedger(sample_every=10_000_000)  # beat cost only
    ledger.on_boundary(0)  # consume the first-boundary sample
    n = 100_000
    t0 = time.perf_counter()
    for i in range(1, n + 1):
        ledger.on_boundary(i)
    per_beat_us = 1e6 * (time.perf_counter() - t0) / n
    idle_prof = ProfilerCapture(tmp)
    t0 = time.perf_counter()
    for i in range(n):
        idle_prof.on_boundary(i)
    per_prof_us = 1e6 * (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    sample_reps = 200
    for _ in range(sample_reps):
        ledger.sample("train_step")
    per_sample_ms = 1e3 * (time.perf_counter() - t0) / sample_reps

    base = statistics.median(base_walls)
    wired = statistics.median(wired_walls)
    overhead_pct = 100.0 * (wired - base) / base
    min_overhead_pct = 100.0 * (min(wired_walls) - min(base_walls)) / min(
        base_walls
    )
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": f"device-truth observability overhead "
                  f"({args.tokens // 1000}k zipf, {dev.platform})",
        "value": round(overhead_pct, 2),
        "unit": "% wall",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "steps_per_run": steps,
        "ledger_samples_per_run": samples,
        "sample_every": args.sample_every,
        "reps": args.reps,
        "base_wall_s": [round(w, 3) for w in base_walls],
        "wired_wall_s": [round(w, 3) for w in wired_walls],
        "wired_loop_wall_s": [round(w, 3) for w in wired_loop_walls],
        "median_base_s": round(base, 3),
        "median_wired_s": round(wired, 3),
        "min_overhead_pct": round(min_overhead_pct, 2),
        "p50_step_ms": round(p50_step_ms, 3),
        "ledger_beat_us": round(per_beat_us, 3),
        "ledger_beat_pct_of_step": round(
            100.0 * per_beat_us / (1e3 * p50_step_ms), 4
        ),
        "profiler_idle_beat_us": round(per_prof_us, 3),
        "ledger_sample_ms": round(per_sample_ms, 4),
        # one sample amortizes over `sample_every` steps
        "ledger_sample_pct_of_cadence": round(
            100.0 * per_sample_ms / (args.sample_every * p50_step_ms), 4
        ),
    }))


if __name__ == "__main__":
    main()
