#!/bin/bash
# Phase-2 TPU measurements (run after tpu_watch2.sh's core sweep):
# full-path quality at flagship dim, BASELINE config-4 shape at scale,
# and the kernel ablation.
cd "$(dirname "$0")/.."
OUT=benchmarks/TPU_R2
probe() { timeout 60 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; }
# the chip is shared: wait for tpu_watch2.sh's core sweep to finish first
# (concurrent benches would corrupt both sets of numbers), then for the tunnel
until grep -q DONE $OUT/sweep2.txt 2>/dev/null; do sleep 110; done
until probe; do sleep 110; done
echo "phase2 start $(date)" >> $OUT/phase2.txt

echo "=== bench fused-table A/B" >> $OUT/phase2.txt
timeout 900 python bench.py --fused 1 --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt
timeout 900 python bench.py --fused 1 --batch-rows 512 --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt

echo "=== bench prng A/B (rbg)" >> $OUT/phase2.txt
timeout 900 python bench.py --prng rbg --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt
timeout 900 python bench.py --prng rbg --fused 1 --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt

echo "=== quality_full flagship (dim=300, band+resident+chunked)" >> $OUT/phase2.txt
timeout 1800 python benchmarks/quality_full.py --tokens 4000000 2>/dev/null | tail -1 >> $OUT/phase2.txt
timeout 1800 python benchmarks/quality_full.py --tokens 4000000 --train-method hs 2>/dev/null | tail -1 >> $OUT/phase2.txt

echo "=== bench BASELINE configs 2/3 (cbow+ns dim=100, sg+hs dim=200)" >> $OUT/phase2.txt
timeout 900 python bench.py --model cbow --dim 100 --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt
timeout 900 python bench.py --train-method hs --dim 200 --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt

echo "=== bench enwik9-shape (100M tokens, w=10)" >> $OUT/phase2.txt
timeout 1800 python bench.py --tokens 100000000 --window 10 --probe-retries 1 2>/dev/null | tail -1 >> $OUT/phase2.txt

echo "=== ablate" >> $OUT/phase2.txt
timeout 900 python benchmarks/ablate.py 2>/dev/null | tail -40 >> $OUT/phase2.txt
echo DONE >> $OUT/phase2.txt
