#!/bin/bash
# Round-5 TPU measurement queue — ordered for SHORT tunnel windows.
#
# Round 4's tunnel was alive ~21 minutes out of 12 hours, in two windows
# (TPU_R4/queue.log). A banked bench item costs ~35-60 s, so the queue is
# tiered by decision value per second:
#
#   Tier 1 — the six numbers that decide the round (VERDICT r4 items 1-2:
#            the true Pallas number, the hs two-tier A/B pair, plus the
#            per-config coverage rows that have never run on chip).
#   Tier 2 — the fresh step-time trace of the CURRENT default path
#            (resident chunked runner; the r2 trace predates it — VERDICT
#            weak item 2). ~3-5 min, after tier 1 so a 4-minute window
#            still banks the A/B numbers.
#   Tier 3 — singles sweep (geometry down-sweep b128/b192, chunk caps,
#            remaining r3/r4 levers never measured on chip).
#   Tier 4 — combos over whichever singles win.
#   Tier 5 — quality-at-scale + the enwik9-shape run (long items).
#   Tier 6 — full_stack retry, LAST: wedged >900 s in compile once
#            (being bisected on CPU this round; see PERF.md).
#
# Re-queued vs TPU_R4: default (the r5 number under the current tree is the
# regression check and the vs_baseline anchor). NOT re-queued: b512 (27.19x,
# measured loss) and fused_kp32_c96 (21.85x, measured loss).
#
# Usage: nohup bash benchmarks/tpu_queue5.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/TPU_R5
. benchmarks/tpu_queue_lib.sh

B='python bench.py --probe-retries 1'
TPU='"platform": "tpu"'
# Forwarding-audit markers (r4 lesson: the first "pallas" artifact was
# INVALID because bench.py's outer->inner re-exec dropped --band-backend and
# silently measured the XLA path). These markers only bank a record whose
# realized plan carries the requested backend — a forwarding regression
# banks nothing and the item retries, instead of banking a mislabeled
# number. JSON key order is stable (platform precedes plan in bench.py's
# record), so one basic-regex grep covers both.
OA='"platform": "tpu".*"band_backend": "pallas_oa"'
PAL='"platform": "tpu".*"band_backend": "pallas"'

# --- tier 1: the decisive six (+ the ISSUE-2 overlap-add kernel) -------------
run_item default              900 "$TPU" $B
run_item pallas               900 "$PAL" $B --band-backend pallas
# Pallas overlap-add (ops/pallas_overlap.py): deletes the 2.14 ms / 26.9%
# layout-copy chain of the r2 step while keeping the sorted table scatter;
# cost model predicts ~-27% step time vs the xla default at this shape
# (PERF.md "Pallas slab-space overlap-add"). The A/B that decides the lever.
run_item pallas_oa            900 "$OA" $B --band-backend pallas_oa
run_item hs_dim200            900 "$TPU" $B --train-method hs --dim 200
run_item hs_dim200_dense512   900 "$TPU" $B --train-method hs --dim 200 --hs-dense-top 512
run_item cbow_dim100          900 "$TPU" $B --model cbow --dim 100
run_item sg_w10               900 "$TPU" $B --window 10

# --- tier 2: fresh trace of the real default path ----------------------------
run_trace /tmp/tr_r5

# --- tier 3: singles ----------------------------------------------------------
# b512 measured BELOW default-256 (27.2x vs 30.4x): the optimum may sit
# under 256 — sweep down; b1024 closes the upward bracket.
run_item b128                 900 "$TPU" $B --batch-rows 128
run_item b192                 900 "$TPU" $B --batch-rows 192
run_item b1024                900 "$TPU" $B --batch-rows 1024
run_item chunk96              900 "$TPU" $B --chunk-cap 96
run_item c192                 900 "$TPU" $B --chunk-cap 192
run_item fused                900 "$TPU" $B --fused 1
run_item kp32                 900 "$TPU" $B --kp 32
run_item rbg                  900 "$TPU" $B --prng rbg
run_item slab_sorted          900 "$TPU" $B --slab-scatter 1
run_item bf16sr               900 "$TPU" $B --table-dtype bfloat16 --sr 1
run_item negbatch_kp256       900 "$TPU" $B --neg-scope batch --kp 256
run_item hs_dim200_dense1024  900 "$TPU" $B --train-method hs --dim 200 --hs-dense-top 1024
# row length L has never been swept on chip (fixed 192 since r1): it sets
# the band-edge waste, mask sizes, and rows-per-step; the corpus's
# 1000-token pseudo-sentences split into ceil(1000/L) rows either way
run_item l384                 900 "$TPU" $B --max-len 384
run_item l512                 900 "$TPU" $B --max-len 512

# --- tier 4: combos -----------------------------------------------------------
# pallas_oa stacks (audited like the single): fused is the stack only this
# backend can take (token-order context grads share the center side's
# sorted index set; the fully-fused kernel and slab scatter cannot fuse
# tables), the rest mirror the pallas combos for a like-for-like read.
run_item pallas_oa_fused      900 "$OA" $B --band-backend pallas_oa --fused 1
run_item pallas_oa_c96        900 "$OA" $B --band-backend pallas_oa --chunk-cap 96
run_item pallas_oa_bf16sr     900 "$OA" $B --band-backend pallas_oa --table-dtype bfloat16 --sr 1
run_item pallas_oa_negbatch   900 "$OA" $B --band-backend pallas_oa --neg-scope batch --kp 256
run_item pallas_c96           900 "$PAL" $B --band-backend pallas --chunk-cap 96
run_item pallas_b512          900 "$PAL" $B --band-backend pallas --batch-rows 512
run_item pallas_bf16sr        900 "$PAL" $B --band-backend pallas --table-dtype bfloat16 --sr 1
run_item pallas_negbatch      900 "$PAL" $B --band-backend pallas --neg-scope batch --kp 256
run_item cbow_dim100_pallas   900 "$PAL" $B --model cbow --dim 100 --band-backend pallas
run_item negbatch_b512        900 "$TPU" $B --neg-scope batch --kp 256 --batch-rows 512
run_item bf16sr_negbatch      900 "$TPU" $B --table-dtype bfloat16 --sr 1 --neg-scope batch --kp 256
run_item fused_kp32           900 "$TPU" $B --fused 1 --kp 32

# --- tier 5: quality at scale + enwik9 shape ---------------------------------
run_item quality_hs_dense512 2400 "$TPU" \
  python benchmarks/quality_full.py --tokens 4000000 --train-method hs --dim 300 --hs-dense-top 512
run_item quality_sg_dim300   2400 "$TPU" \
  python benchmarks/quality_full.py --tokens 4000000
run_item quality_analogy_dim300 2400 "$TPU" \
  python benchmarks/quality_full.py --analogy --tokens 4000000
run_item enwik9_100M         3600 "$TPU" $B --tokens 100000000 --window 10 --run-timeout 3000

# --- tier 6: the compile-wedge retry, last -----------------------------------
run_item full_stack          1800 "$TPU" $B --fused 1 --chunk-cap 96 --neg-scope batch --kp 256 --table-dtype bfloat16 --sr 1

echo "$(date -u +%FT%TZ) QUEUE5 COMPLETE after $FAILED_PROBES failed probes total" >> "$LOG"
