#!/usr/bin/env python
"""Bisect the full_stack compile wedge on CPU (VERDICT r4 item 2).

The on-chip item `full_stack` (--fused 1 --chunk-cap 96 --neg-scope batch
--kp 256 --table-dtype bfloat16 --sr 1) wedged >900 s in XLA compile on
TPU (TPU_R4/queue.log 04:04 FAILED) while every constituent single
compiled in seconds. This harness times LOWER + COMPILE (no execute) of
the resident chunk runner for each lever subset on the CPU backend, so
the exploding lever pair can be named without burning tunnel time.

CPU and TPU run different XLA backends, so a CPU wedge is evidence, not
proof — but a combinatorial pass-size explosion (the plausible cause:
fused [V,2,d] tables x batch-scoped scatter x bf16 SR round-trip inside
one scan body) shows up as a superlinear compile-time jump on any
backend.

Writes one JSON line per combo to stdout and a summary table to stderr.

Usage: JAX_PLATFORMS=cpu python benchmarks/compile_bisect.py [--quick]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

LEVERS = {
    "fused": {"fused_tables": True},
    "c96": {"_chunk_cap": 96},
    "negbatch": {"negative_scope": "batch", "shared_negatives": 256},
    "bf16sr": {"dtype": "bfloat16", "stochastic_rounding": True},
}

_CORPUS_CACHE: dict = {}


def compile_combo(names: tuple, vocab_size: int, tokens: int) -> dict:
    import jax

    # the axon sitecustomize overrides the JAX_PLATFORMS env var; a
    # config.update after import wins over both (same trick as bench.py)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import BatchIterator, PackedCorpus
    from word2vec_tpu.models.params import init_params
    from word2vec_tpu.ops import resident as res
    from word2vec_tpu.ops.tables import DeviceTables
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    overrides: dict = {}
    chunk_cap = 32
    for n in names:
        for k, v in LEVERS[n].items():
            if k == "_chunk_cap":
                chunk_cap = v
            else:
                overrides[k] = v

    cfg = Word2VecConfig(
        model="sg", train_method="ns", negative=5, word_dim=300,
        window=5, subsample_threshold=1e-4, batch_rows=256,
        max_sentence_len=192, **overrides,
    )
    key = (vocab_size, tokens)
    if _CORPUS_CACHE.get("key") != key:
        vocab = zipf_vocab(vocab_size, 17_000_000)
        ids = zipf_corpus_ids(vocab, tokens, seed=0)
        _CORPUS_CACHE.update(
            key=key, vocab=vocab,
            corpus=PackedCorpus.pack(ids, cfg.max_sentence_len),
        )
    vocab = _CORPUS_CACHE["vocab"]
    corpus = _CORPUS_CACHE["corpus"]
    tables = DeviceTables.build(vocab, cfg)
    params = init_params(cfg, len(vocab), jax.random.key(0))
    batcher = BatchIterator(corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1)
    S, _ = cfg.chunk_geometry(batcher.steps_per_epoch(), cap=chunk_cap)
    alphas = jnp.full((S,), cfg.init_alpha, jnp.float32)
    corpus_dev = res.device_corpus(corpus)
    order_dev = jnp.asarray(
        res.epoch_order(1, 0, corpus.num_rows).astype(np.int32)
    )
    fn = jax.jit(
        res.make_resident_chunk_runner(cfg, tables), donate_argnums=0
    )

    t0 = time.perf_counter()
    lowered = fn.lower(
        params, corpus_dev, order_dev, jax.random.key(7), 0, 0, alphas
    )
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    # HLO size proxies: a pass-size explosion shows up in instruction count
    # even when this backend's pass pipeline doesn't wedge on it
    try:
        hlo_lines = len(compiled.as_text().splitlines())
    except Exception:  # noqa: BLE001 — size proxy only
        hlo_lines = -1
    return {
        "combo": "+".join(names) if names else "none",
        "S": int(S),
        "lower_s": round(t_lower, 2),
        "compile_s": round(t_compile, 2),
        "hlo_lines": hlo_lines,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small vocab/corpus (shape-independent wedges only)")
    ap.add_argument("--vocab", type=int, default=0)
    ap.add_argument("--tokens", type=int, default=0)
    args = ap.parse_args()
    vocab = args.vocab or (8000 if args.quick else 71000)
    tokens = args.tokens or (400_000 if args.quick else 2_000_000)

    names = list(LEVERS)
    combos = [()]
    combos += [(n,) for n in names]
    combos += list(itertools.combinations(names, 2))
    combos += list(itertools.combinations(names, 3))
    combos += [tuple(names)]

    rows = []
    for combo in combos:
        try:
            rec = compile_combo(combo, vocab, tokens)
        except Exception as e:  # noqa: BLE001 — record and continue
            rec = {"combo": "+".join(combo) if combo else "none",
                   "error": f"{type(e).__name__}: {e}"}
        rows.append(rec)
        print(json.dumps(rec), flush=True)

    print("\ncombo                          lower_s  compile_s  hlo_lines",
          file=sys.stderr)
    for r in rows:
        if "error" in r:
            print(f"{r['combo']:30s} ERROR {r['error'][:60]}",
                  file=sys.stderr)
        else:
            print(f"{r['combo']:30s} {r['lower_s']:7.2f} {r['compile_s']:9.2f}"
                  f" {r['hlo_lines']:10d}", file=sys.stderr)


if __name__ == "__main__":
    main()
