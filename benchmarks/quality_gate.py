#!/usr/bin/env python
"""CI quality gate: the band-degeneracy collapse as an enforced contract.

Round 5 found the flagship band kernel's real quality failure
(benchmarks/BAND_DEGENERACY_r5.md): on a degenerate over-trained tiny-vocab
corpus the shared negative pool collapses planted analogy structure
(accuracy 0.0 vs the pair kernel's 0.74 on the identical stream), and until
this gate that was a warning plus a banked table. This harness runs the
fast graded Spearman + analogy legs on synthetic corpora spanning the
severity axis (vocab size x occurrences/word) through the REAL CLI, per PR:

  degenerate band   — the 864-word planted-analogy grid over-trained to
                      ~14k occ/word at dim 300 (the r5 collapse shape,
                      CPU-recalibrated: measured 0.0854 here): --kernel
                      band must score <= --band-max (0.1). kernel='auto'
                      would refuse this shape (select_kernel), so the leg
                      FORCES band — which is exactly what the gate exists
                      to fence.
  degenerate pair   — the same stream under --kernel auto: the planner
                      must auto-select 'pair' (asserted from the manifest)
                      and score >= --pair-min (0.7).
  safe band         — the same grid shape inside the safe region
                      (~2.3k occ/word): band must hold >= --safe-min
                      (0.95) — the gate must not fence the fast path out
                      of its measured-good domain.
  sentinel          — the collapse reproduction under the live sentinel:
                      --quality-probe-every + --quality-budget on the
                      degenerate band shape must abort rc=3 mid-collapse
                      with flight.json (reason quality_alert) carrying the
                      probe rows and the manifest marked quality_degraded.

Emits one JSON line per leg plus a final {"gate": "pass"|"fail"} line;
exits non-zero on any failed assertion. ~10 min on a CI core at the
default shape; --fast shrinks dim for local iteration (thresholds then
NOT asserted — the calibration holds at dim 300).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def emit(rec: dict) -> None:
    print(json.dumps(rec), flush=True)


def train_cli(workdir, corpus_path, out_path, *, dim, iters, kernel,
              extra=(), timeout=1800):
    """One real-CLI training run; returns (rc, stderr_tail)."""
    cmd = [
        sys.executable, "-m", "word2vec_tpu.cli",
        "-train", corpus_path, "-output", out_path, "--quiet",
        "-model", "sg", "-train_method", "ns", "-negative", "5",
        "-size", str(dim), "-window", "5", "-iter", str(iters),
        "-min-count", "5", "-subsample", "1e-4",
        "--backend", "cpu", "--chunk-steps", "0",
        "--kernel", kernel,
    ] + list(extra)
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    run = subprocess.run(
        cmd, cwd=workdir, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    return run.returncode, run.stderr.strip().splitlines()[-8:]


def score(vec_path, questions) -> dict:
    import numpy as np

    from word2vec_tpu.data.vocab import Vocab
    from word2vec_tpu.eval.analogy import evaluate_analogy_sections
    from word2vec_tpu.io.embeddings import load_embeddings_text

    words, W = load_embeddings_text(vec_path)
    vocab = Vocab(list(words), np.ones(len(words), dtype=np.int64))
    r = evaluate_analogy_sections(
        W, vocab, [("planted", list(questions))], restrict_vocab=len(vocab)
    )
    return {
        "analogy_accuracy": round(r.accuracy, 4),
        "mean_gold_rank": round(r.mean_gold_rank, 2),
        "total": r.total,
        "skipped_oov": r.skipped_oov,
        "skipped_degenerate": r.skipped_degenerate,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=2_000_000)
    ap.add_argument("--dim", type=int, default=300)
    ap.add_argument("--degenerate-iters", type=int, default=6,
                    help="epochs of the collapse legs (~14k occ/word at "
                    "the default grid — past the measured onset)")
    ap.add_argument("--safe-iters", type=int, default=1,
                    help="epochs of the safe leg (~2.3k occ/word — below "
                    "the measured onset)")
    ap.add_argument("--band-max", type=float, default=0.1)
    ap.add_argument("--pair-min", type=float, default=0.7)
    ap.add_argument("--safe-min", type=float, default=0.95)
    ap.add_argument("--probe-every", type=int, default=8,
                    help="sentinel-leg probe cadence in step-counter "
                    "units; 8 fires at every ~21-step chunk boundary of "
                    "the default shape, catching the measured collapse "
                    "trajectory (0.99 at step 21 -> 0.48 at 63 -> 0.08 "
                    "plateau) mid-run")
    ap.add_argument("--budget", type=int, default=2)
    ap.add_argument("--skip-sentinel", action="store_true")
    ap.add_argument("--fast", action="store_true",
                    help="dim 64 local iteration preset: runs every leg "
                    "but DOES NOT assert the thresholds (the collapse "
                    "calibration holds at dim 300: band asymptotes ~0.13 "
                    "at dim 64)")
    ap.add_argument("--timeout", type=float, default=1800.0)
    args = ap.parse_args()
    if args.fast:
        args.dim = 64

    from word2vec_tpu.utils.synthetic import analogy_corpus

    # the r5 collapse grid: 16x4 cells, 40-word pools -> ~864-word vocab
    tokens, questions = analogy_corpus(
        n_rows=16, n_cols=4, words_per_pool=40,
        n_tokens=args.tokens, seed=0,
    )
    failures = []
    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        corpus = os.path.join(tmp, "grid.txt")
        with open(corpus, "w") as f:
            f.write(" ".join(tokens))

        def leg(name, *, kernel, iters, expect_rc=0, extra=(),
                metrics_dir=None):
            t0 = time.perf_counter()
            vec = os.path.join(tmp, f"{name}.txt")
            ex = list(extra)
            if metrics_dir:
                ex += ["--metrics-dir", metrics_dir]
            rc, err = train_cli(
                tmp, corpus, vec, dim=args.dim, iters=iters, kernel=kernel,
                extra=ex, timeout=args.timeout,
            )
            rec = {
                "leg": name, "kernel": kernel, "iters": iters, "rc": rc,
                "wall_s": round(time.perf_counter() - t0, 1),
            }
            if rc != expect_rc:
                rec["error"] = f"rc={rc} (expected {expect_rc})"
                rec["stderr_tail"] = err
            elif expect_rc == 0:
                rec.update(score(vec, questions))
            emit(rec)
            results[name] = rec
            return rec

        # --- degenerate band: the collapse itself --------------------------
        rec = leg("degenerate_band", kernel="band",
                  iters=args.degenerate_iters)
        if "error" in rec:
            failures.append("degenerate_band failed to run")
        elif not args.fast and rec["analogy_accuracy"] > args.band_max:
            failures.append(
                f"band did NOT collapse: {rec['analogy_accuracy']} > "
                f"{args.band_max} — the degeneracy reproduction is broken"
            )

        # --- degenerate pair (via kernel=auto): the fix --------------------
        mdir = os.path.join(tmp, "mdir_pair")
        rec = leg("degenerate_pair_auto", kernel="auto",
                  iters=args.degenerate_iters, metrics_dir=mdir)
        if "error" in rec:
            failures.append("degenerate_pair_auto failed to run")
        else:
            man = json.load(open(os.path.join(mdir, "manifest.json")))
            rec["manifest_kernel"] = man.get("kernel")
            rec["kernel_decision"] = (man.get("kernel_decision") or {}).get(
                "selected"
            )
            emit({"leg": "planner_selection", **{
                k: rec[k] for k in ("manifest_kernel", "kernel_decision")
            }})
            if man.get("kernel") != "pair":
                failures.append(
                    f"planner did not auto-select pair inside the domain "
                    f"(manifest kernel={man.get('kernel')!r})"
                )
            if not args.fast and rec["analogy_accuracy"] < args.pair_min:
                failures.append(
                    f"pair did not hold: {rec['analogy_accuracy']} < "
                    f"{args.pair_min}"
                )

        # --- safe region: band must stay fast AND good ---------------------
        rec = leg("safe_band", kernel="band", iters=args.safe_iters)
        if "error" in rec:
            failures.append("safe_band failed to run")
        elif not args.fast and rec["analogy_accuracy"] < args.safe_min:
            failures.append(
                f"band regressed in the safe region: "
                f"{rec['analogy_accuracy']} < {args.safe_min}"
            )

        # --- sentinel: the collapse caught LIVE, rc=3 ----------------------
        if not args.skip_sentinel:
            mdir = os.path.join(tmp, "mdir_sentinel")
            rec = leg(
                "sentinel", kernel="band", iters=args.degenerate_iters,
                expect_rc=3, metrics_dir=mdir,
                extra=[
                    "--quality-probe-every", str(args.probe_every),
                    "--quality-budget", str(args.budget),
                    "--quality-floor", "0.7", "--quality-drop", "0.5",
                    "--quality-grace", "2",
                ],
            )
            if "error" in rec:
                failures.append(
                    "sentinel leg did not abort rc=3 on the collapse"
                )
            else:
                fl = json.load(open(os.path.join(mdir, "flight.json")))
                man = json.load(open(os.path.join(mdir, "manifest.json")))
                probe_rows = [
                    r for r in fl.get("quality", [])
                    if "quality_analogy_accuracy" in r
                ]
                rec2 = {
                    "leg": "sentinel_artifacts",
                    "flight_reason": fl.get("reason"),
                    "probe_rows": len(probe_rows),
                    "manifest_shutdown": man.get("shutdown"),
                    "alert": man.get("quality_alert"),
                }
                emit(rec2)
                if fl.get("reason") != "quality_alert" or not probe_rows:
                    failures.append(
                        "flight.json missing quality_alert reason or "
                        "probe rows"
                    )
                if man.get("shutdown") != "quality_degraded":
                    failures.append("manifest not marked quality_degraded")

    emit({
        "gate": "fail" if failures else "pass",
        "failures": failures,
        "thresholds": {
            "band_max": args.band_max, "pair_min": args.pair_min,
            "safe_min": args.safe_min,
        },
        "asserted": not args.fast,
    })
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
