#!/usr/bin/env python
"""Rank the banked round-3 on-chip results (benchmarks/TPU_R3/*.json).

Prints a words/sec table sorted best-first with vs_baseline and the lever
deltas vs the banked default, so promoting winners to config defaults is a
read-off. Run any time; the queue (tpu_queue3.sh) banks items as the tunnel
allows.
"""

from __future__ import annotations

import glob
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))


def main() -> None:
    rows = []
    for path in sorted(glob.glob(os.path.join(HERE, "TPU_R3", "*.json"))):
        name = os.path.basename(path)[:-5]
        try:
            with open(path) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(rec.get("value"), (int, float)):
            rows.append((name, rec))
    if not rows:
        print("no banked results yet (tunnel down?); see TPU_R3/queue.log")
        return
    bench = [(n, r) for n, r in rows if "words/sec" in r.get("metric", "")]
    base = dict(bench).get("default")
    bench.sort(key=lambda nr: -nr[1]["value"])
    print(f"{'item':28s} {'words/sec':>12s} {'vs_base':>8s} {'vs_default':>10s}")
    for name, r in bench:
        delta = (
            f"{r['value'] / base['value'] - 1:+.1%}"
            if base and name != "default" else ""
        )
        vs = r.get("vs_baseline")
        print(f"{name:28s} {r['value']:12,.0f} "
              f"{vs if vs is not None else '':>8} {delta:>10s}")
    others = [(n, r) for n, r in rows if (n, r) not in bench]
    for name, r in others:
        print(f"{name}: {json.dumps(r)[:160]}")


if __name__ == "__main__":
    main()
