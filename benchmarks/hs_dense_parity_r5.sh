#!/bin/bash
# VERDICT r4 item 5: the hs dense-top=512 parity row showed delta_margin
# +0.0405 — 2x the calibrated ±0.02 noise band — on ONE corpus draw, and
# the promotion rule accepts positive deltas asymmetrically. Before that
# asymmetry can stand, the delta must replicate across corpora with
# DIFFERENT structures (topic counts, sharing rates, zipf exponents,
# seeds), and the one-tier kernel must be measured on the SAME corpora to
# separate "the two-tier update changes dynamics" from "ours-vs-reference
# hs offset on this corpus family".
#
# 4 corpus structures x {dense-top=512, one-tier} = 8 rows.
# Usage: bash benchmarks/hs_dense_parity_r5.sh > benchmarks/PARITY_HS_DENSE_r5.jsonl
cd "$(dirname "$0")/.." || exit 1
P="python benchmarks/parity.py --tokens 200000 --dim 64 --iters 5 --model sg --train-method hs"

CORPORA=(
  ""                                                                      # r4's structure, seed 0 (continuity row)
  "--seed 1"                                                              # same structure, fresh draw
  "--corpus-topics 16 --corpus-words-per-topic 25 --corpus-p-shared 0.4 --corpus-zipf 0.8 --seed 2"
  "--corpus-topics 4 --corpus-words-per-topic 80 --corpus-p-shared 0.15 --corpus-zipf 1.3 --corpus-span 30 --seed 3"
)

for c in "${CORPORA[@]}"; do
  for tier in "--hs-dense-top 512" ""; do
    echo "## hs parity $c $tier" >&2
    timeout 1800 $P $c $tier 2>/dev/null | tail -1
  done
done
