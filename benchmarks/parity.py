#!/usr/bin/env python
"""Accuracy-parity harness: this framework vs the compiled C++ reference.

BASELINE.md's accuracy gate is "WS-353 / Google-analogy scores within ±1% of
the CPU reference". With no network there is no text8 and no WS-353 file, so
parity is measured the way SURVEY §7(e) prescribes — statistically, on a
corpus with PLANTED structure:

1. generate a topic corpus (utils/synthetic.topic_corpus): same-topic words
   co-occur in spans, cross-topic words only via shared function words;
2. train the reference binary (built by reference_harness/measure_baseline.py
   machinery against the eigen-lite shim) and this framework's CLI on the
   SAME token stream with the SAME hyperparameters;
3. score both with the SAME eval: Spearman of embedding cosines against the
   planted same/cross-topic golds (WS-353 protocol), plus top-10 neighbor
   topic purity;
4. report both scores and their deltas as one JSON line.

Parity holds when the deltas are within noise across seeds (the reference's
random_device seeding, Word2Vec.cpp:16, makes bitwise comparison impossible
— SURVEY §7(e)).

Usage: python benchmarks/parity.py [--tokens 200000] [--dim 64] [--iters 5]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(HERE, "reference_harness"))


def neighbor_purity(
    words, W, topic_of, k: int = 10, sample: int = 100, seed: int = 0
) -> float:
    """Mean fraction of a content word's top-k cosine neighbors (among other
    content words) sharing its topic."""
    idx = {w: i for i, w in enumerate(words)}
    content = [w for w in words if w in topic_of]
    rng = np.random.default_rng(seed)
    probe = rng.choice(content, size=min(sample, len(content)), replace=False)
    C = W[[idx[w] for w in content]]
    C = C / np.maximum(np.linalg.norm(C, axis=1, keepdims=True), 1e-12)
    pos = {w: i for i, w in enumerate(content)}
    purities = []
    for w in probe:
        sims = C @ C[pos[w]]
        sims[pos[w]] = -np.inf
        top = np.argpartition(-sims, k)[:k]
        same = sum(topic_of[content[int(t)]] == topic_of[w] for t in top)
        purities.append(same / k)
    return float(np.mean(purities))


def _load_pair_cosines(path: str, pairs, min_pairs: int = 1):
    """Shared loader for the pair-based evals: saved text vectors ->
    (words, W, cosines, golds) with the OOV-drop protocol, or an error
    dict (the one place the empty-matrix and OOV special cases live, so
    the topic and graded paths cannot drift apart)."""
    from word2vec_tpu.eval.similarity import cosine_rows
    from word2vec_tpu.io.embeddings import load_embeddings_text

    words, W = load_embeddings_text(path)
    if W.size == 0:
        # The reference writes a "0 0" matrix for cbow+hs: init_weights
        # allocates C only under ns (Word2Vec.cpp:208-209) yet main.cpp:199
        # saves C for hs+cbow. Our framework fixes this (SURVEY §2 latent
        # bug), so in this config parity is ours-absolute, not a delta.
        return {"error": "empty embedding matrix (reference cbow+hs latent bug)"}
    idx = {w: i for i, w in enumerate(words)}
    ii, jj, gold = [], [], []
    for a, b, s in pairs:
        if a in idx and b in idx:
            ii.append(idx[a])
            jj.append(idx[b])
            gold.append(s)
    if len(ii) < min_pairs:
        return {"error": f"eval pairs OOV at this budget ({len(ii)} usable)"}
    cos = cosine_rows(W, np.asarray(ii), np.asarray(jj))
    if not np.isfinite(cos).all():
        # a diverged model (NaN/inf rows) must fail the eval loudly —
        # rank statistics over NaNs produce arbitrary values (the r5 clip
        # sweep's tau=0 run scored a spurious spearman_graded of 1.0 on a
        # NaN-margin model before this guard)
        bad = int((~np.isfinite(cos)).sum())
        return {"error": f"non-finite cosines for {bad}/{len(cos)} pairs "
                "(diverged model)"}
    return words, W, cos, np.asarray(gold, np.float64)


def eval_vectors(path: str, pairs, topic_of) -> dict:
    from word2vec_tpu.eval.similarity import spearman

    loaded = _load_pair_cosines(path, pairs)
    if isinstance(loaded, dict):
        return loaded
    words, W, cos, gold_arr = loaded
    # split at the midpoint of the gold range, NOT the median: with the
    # two-level golds an OOV-dropped high pair shifts the median onto the
    # low level and `>= median` would select every pair (empty cross side,
    # NaN margin — observed at reduced budgets). If OOV drops an entire
    # level the margin is undefined; report null rather than NaN.
    hi = gold_arr > (gold_arr.min() + gold_arr.max()) / 2.0
    margin = (
        round(float(cos[hi].mean() - cos[~hi].mean()), 4)
        if hi.any() and (~hi).any() else None
    )
    return {
        "spearman": round(spearman(cos, gold_arr), 4),
        # Spearman saturates at its tie-ceiling (~0.866 for the two-level
        # gold) once the structure is fully recovered; the margin is the
        # CONTINUOUS sensitivity metric — mean cosine separation between
        # same-topic and cross-topic pairs — so small quality regressions
        # remain visible after both sides hit the ceiling.
        "cos_margin": margin,
        "pairs_used": len(gold_arr),
        "pairs_total": len(pairs),
        "neighbor_purity@10": round(neighbor_purity(words, W, topic_of), 4),
    }


def eval_graded_vectors(path: str, pairs) -> dict:
    """Score saved vectors against GRADED planted golds
    (utils/synthetic.graded_pair_corpus): Spearman of pair cosines vs the
    unique-alpha grid. Unlike the two-level topic golds there is no tie
    ceiling — the metric moves continuously with recovery quality, so it
    discriminates between configs even when both have fully learned the
    coarse topic split (VERDICT r4 weak item 5)."""
    from word2vec_tpu.eval.similarity import pearson, spearman

    loaded = _load_pair_cosines(path, pairs, min_pairs=3)
    if isinstance(loaded, dict):
        return loaded
    _words, _W, cos, gold_arr = loaded
    return {
        "spearman_graded": round(spearman(cos, gold_arr), 4),
        "pearson_graded": round(pearson(cos, gold_arr), 4),
        "pairs_used": len(gold_arr),
        "pairs_total": len(pairs),
    }


def eval_analogy_vectors(path: str, questions) -> dict:
    """Score saved text vectors on planted-relation analogy questions with
    the SAME 3CosAdd path the CLI's --eval-analogy uses (eval/analogy.py).
    Completes the Google-analogy half of the BASELINE.json accuracy gate:
    the reference ships no eval at all (README.md:1-14), so parity is both
    sides scored on identical generated questions."""
    from word2vec_tpu.data.vocab import Vocab
    from word2vec_tpu.eval.analogy import evaluate_analogy_sections
    from word2vec_tpu.io.embeddings import load_embeddings_text

    words, W = load_embeddings_text(path)
    if W.size == 0:
        return {"error": "empty embedding matrix (reference cbow+hs latent bug)"}
    # saved word2vec files are count-sorted, so index order is frequency
    # order and restrict_vocab keeps its most-frequent-N meaning
    vocab = Vocab(list(words), np.ones(len(words), dtype=np.int64))
    r = evaluate_analogy_sections(
        W, vocab, [("planted-relations", list(questions))]
    )
    return {
        "analogy_accuracy": round(r.accuracy, 4),
        "correct": r.correct,
        "total": r.total,
        "skipped_oov": r.skipped_oov,
        # unanswerable-by-construction questions (gold repeats a question
        # word): banked so a degenerate probe set can't pass silently
        "skipped_degenerate": r.skipped_degenerate,
        # continuous sensitivity metric: stays informative after both sides
        # reach accuracy 1.0 (the instrument must not saturate)
        "mean_gold_rank": round(r.mean_gold_rank, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=200_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--window", type=int, default=5)
    ap.add_argument("--negative", type=int, default=5)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--min-count", type=int, default=5)
    ap.add_argument("--subsample", type=float, default=1e-4)
    ap.add_argument("--model", choices=["sg", "cbow"], default="sg")
    ap.add_argument("--train-method", choices=["ns", "hs"], default="ns")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--kernel", choices=["auto", "band", "pair"], default="auto",
                    help="device kernel for OUR side (reference has no analog)")
    ap.add_argument("--shared-negatives", type=int, default=64,
                    help="band-kernel shared draws per row for OUR side")
    ap.add_argument("--negative-scope", choices=["row", "batch"],
                    default="row", help="negative pool scope for OUR side")
    ap.add_argument("--slab-scatter", type=int, default=0, choices=[0, 1],
                    help="band-kernel slab-space context scatter for OUR side")
    ap.add_argument("--band-backend", choices=["xla", "pallas"],
                    default="xla",
                    help="band-step compute backend for OUR side")
    ap.add_argument("--prng", choices=["threefry", "rbg"], default="threefry",
                    help="jax PRNG impl for OUR side (CLI --prng)")
    ap.add_argument("--table-dtype", choices=["float32", "bfloat16"],
                    default="float32",
                    help="table storage dtype for OUR side")
    ap.add_argument("--hs-dense-top", type=int, default=0,
                    help="two-tier hs dense tier (config.hs_dense_top)")
    ap.add_argument("--sr", type=int, default=0, choices=[0, 1],
                    help="stochastic rounding for OUR side (bf16 tables)")
    ap.add_argument("--skip-reference", action="store_true",
                    help="evaluate only this framework (no g++/reference)")
    ap.add_argument("--analogy", action="store_true",
                    help="analogy-parity mode: train both sides on the "
                    "planted-RELATION corpus (utils/synthetic.analogy_corpus) "
                    "and gate 3CosAdd accuracy instead of similarity Spearman "
                    "— the Google-analogy half of the BASELINE accuracy gate")
    ap.add_argument("--corpus-topics", type=int, default=8,
                    help="topic-corpus structure knob (VERDICT r5 item: the "
                    "hs dense-top delta must be replicated across corpora "
                    "with DIFFERENT structures, not one favorable draw)")
    ap.add_argument("--corpus-words-per-topic", type=int, default=40)
    ap.add_argument("--corpus-p-shared", type=float, default=0.25)
    ap.add_argument("--corpus-span", type=int, default=20)
    ap.add_argument("--corpus-zipf", type=float, default=1.0,
                    help="zipf exponent of the within-topic word draw")
    ap.add_argument("--graded", action="store_true",
                    help="graded-similarity mode: train both sides on the "
                    "graded-overlap pair corpus "
                    "(utils/synthetic.graded_pair_corpus) and gate Spearman "
                    "vs UNIQUE-rank golds — no tie ceiling (r5; VERDICT r4 "
                    "weak item 5)")
    args = ap.parse_args()

    from measure_baseline import build  # reference_harness

    from word2vec_tpu.utils.synthetic import (
        analogy_corpus, graded_pair_corpus, topic_corpus,
        topic_similarity_pairs,
    )

    if args.analogy:
        tokens, questions = analogy_corpus(n_tokens=args.tokens, seed=args.seed)
        evaluate = lambda path: eval_analogy_vectors(path, questions)  # noqa: E731
        corpus_name = f"analogy-synthetic-{args.tokens} tokens"
    elif args.graded:
        tokens, gpairs = graded_pair_corpus(n_tokens=args.tokens, seed=args.seed)
        evaluate = lambda path: eval_graded_vectors(path, gpairs)  # noqa: E731
        corpus_name = f"graded-synthetic-{args.tokens} tokens"
    else:
        tokens, topic_of = topic_corpus(
            n_topics=args.corpus_topics,
            words_per_topic=args.corpus_words_per_topic,
            n_tokens=args.tokens,
            span_len=args.corpus_span,
            p_shared=args.corpus_p_shared,
            zipf_exponent=args.corpus_zipf,
            seed=args.seed,
        )
        pairs = topic_similarity_pairs(topic_of, seed=args.seed + 1)
        evaluate = lambda path: eval_vectors(path, pairs, topic_of)  # noqa: E731
        corpus_name = (
            f"topic-synthetic-{args.tokens} tokens"
            f" (T={args.corpus_topics} wpt={args.corpus_words_per_topic}"
            f" ps={args.corpus_p_shared} span={args.corpus_span}"
            f" zipf={args.corpus_zipf} seed={args.seed})"
        )

    if args.train_method == "hs":
        args.negative = 0
    result = {
        "config": f"{args.model}+{args.train_method} k={args.negative} "
        f"dim={args.dim} w={args.window} iter={args.iters} "
        f"subsample={args.subsample} kernel={args.kernel} "
        f"backend={args.band_backend} "
        f"kp={args.shared_negatives} scope={args.negative_scope} "
        f"dtype={args.table_dtype} sr={args.sr} "
        f"slab={args.slab_scatter} prng={args.prng} "
        f"dense-top={args.hs_dense_top}",
        "corpus": corpus_name,
    }
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "text8"), "w") as f:
            f.write(" ".join(tokens))

        common = [
            "-train", "text8", "-model", args.model,
            "-train_method", args.train_method,
            "-negative", str(args.negative), "-size", str(args.dim),
            "-window", str(args.window), "-subsample", str(args.subsample),
            "-iter", str(args.iters), "-min-count", str(args.min_count),
        ]

        if not args.skip_reference:
            # A missing/unbuildable reference degrades to a structured
            # error instead of killing the harness: our side still trains
            # and scores, so absolute-floor gates (and environments without
            # /root/reference mounted) keep working — the same shape the
            # reference's own cbow+hs latent bug already produces.
            try:
                exe = build(tmp)
                subprocess.run(
                    [exe, *common, "-output", "vec_ref.txt", "-threads", "1"],
                    cwd=tmp, check=True, capture_output=True,
                )
                result["reference"] = evaluate(os.path.join(tmp, "vec_ref.txt"))
            except (subprocess.CalledProcessError, OSError) as e:
                from measure_baseline import REFERENCE

                missing = not os.path.exists(
                    os.path.join(REFERENCE, "Word2Vec.cpp")
                )
                result["reference"] = {
                    "error": (
                        f"reference source tree {REFERENCE} not present in "
                        "this environment"
                        if missing else
                        f"reference build/run failed: {e}"
                    ),
                }

        subprocess.run(
            [
                sys.executable, "-m", "word2vec_tpu.cli", *common,
                "-output", "vec_ours.txt", "--backend", "cpu", "--quiet",
                "--kernel", args.kernel,
                "--shared-negatives", str(args.shared_negatives),
                "--negative-scope", args.negative_scope,
                "--slab-scatter", str(args.slab_scatter),
                "--band-backend", args.band_backend,
                "--prng", args.prng,
                "--table-dtype", args.table_dtype,
                "--stochastic-rounding", str(args.sr),
                "--hs-dense-top", str(args.hs_dense_top),
            ],
            cwd=tmp, check=True, capture_output=True,
            env={**os.environ, "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
        )
        result["ours"] = evaluate(os.path.join(tmp, "vec_ours.txt"))

    if (
        "reference" in result
        and "error" not in result["reference"]
        and "error" not in result.get("ours", {})
    ):
        if args.graded:
            result["delta_spearman_graded"] = round(
                result["ours"]["spearman_graded"]
                - result["reference"]["spearman_graded"], 4
            )
        elif args.analogy:
            result["delta_accuracy"] = round(
                result["ours"]["analogy_accuracy"]
                - result["reference"]["analogy_accuracy"], 4
            )
            result["delta_gold_rank"] = round(
                result["ours"]["mean_gold_rank"]
                - result["reference"]["mean_gold_rank"], 3
            )
        else:
            result["delta_spearman"] = round(
                result["ours"]["spearman"] - result["reference"]["spearman"], 4
            )
            result["delta_purity"] = round(
                result["ours"]["neighbor_purity@10"]
                - result["reference"]["neighbor_purity@10"], 4
            )
            m_ours = result["ours"]["cos_margin"]
            m_ref = result["reference"]["cos_margin"]
            result["delta_margin"] = (
                round(m_ours - m_ref, 4)
                if m_ours is not None and m_ref is not None else None
            )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
