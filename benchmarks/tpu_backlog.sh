#!/bin/bash
# One-shot TPU measurement backlog (run when the tunnel is up).
# Captures every pending on-chip number for round 2 into benchmarks/TPU_R2/.
# Each step is independently time-boxed; a tunnel hang mid-run skips to the
# next item rather than wedging the whole sweep.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/TPU_R2
mkdir -p "$OUT"

run() {  # run <name> <timeout_s> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "=== $name: $*" | tee -a "$OUT/log.txt"
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>&1
  echo "rc=$? $(tail -1 "$OUT/$name.out")" | tee -a "$OUT/log.txt"
}

# 1. headline bench, chunked dispatch (overlap-add vs slab scatter A/B)
run bench_default      900 python bench.py
run bench_slab         900 python bench.py --slab-scatter 1
# 2. geometry exploration (fixed-cost amortization)
run bench_rows512      900 python bench.py --batch-rows 512
run bench_len384       900 python bench.py --max-len 384
run bench_slab_rows512 900 python bench.py --slab-scatter 1 --batch-rows 512
# 2a2. band slab geometry (auto S=118 vs row-aligned alternatives)
run bench_bandS96      900 python bench.py --slab-scatter 1 --band-chunk 96
run bench_bandS64      900 python bench.py --slab-scatter 1 --band-chunk 64
# 2b. shared-negative width (parity holds to KP=8 on the harness)
run bench_kp32         900 python bench.py --slab-scatter 1 --kp 32
run bench_kp16         900 python bench.py --slab-scatter 1 --kp 16
# 3. isolated slab-scatter experiment + kernel ablation
run exp_slab           600 python benchmarks/exp_slab_scatter.py
run ablate             900 python benchmarks/ablate.py
# 4. op-level traces for both scatter modes
run trace_default      600 python benchmarks/trace_tools.py capture --out /tmp/tr_default
run trace_report       300 python benchmarks/trace_tools.py report /tmp/tr_default
# 5. scale rehearsal: sustained run at the BASELINE config-4 shape
run bench_100m        1800 python bench.py --tokens 100000000 --window 10
echo "backlog complete; results in $OUT/" | tee -a "$OUT/log.txt"
