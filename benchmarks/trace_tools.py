#!/usr/bin/env python
"""On-device op-level profiling: capture a jax.profiler trace of the train
step and print the TPU op breakdown (time per fused op, copies, scatters).

The tensorboard-plugin-profile converter in this image is broken
(protobuf/_pywrap mismatch), so the xplane.pb is parsed directly with the
tensorflow.tsl protobuf bindings. Requires
PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python (set automatically below).

Usage:
  python benchmarks/trace_tools.py capture [--steps 10] [--dim 300] ...
  python benchmarks/trace_tools.py report /tmp/w2vtrace

`capture` traces the flagship band-kernel step on whatever device JAX
resolves and then reports. Use `report` on an existing trace directory.
The main diagnostic use: find layout copies (%copy.* on [B, L, d]) and
scatter fusions worth restructuring (VERDICT r1 item "what's weak" 4).
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import sys

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def capture(args) -> str:
    import jax
    import jax.numpy as jnp

    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import BatchIterator, PackedCorpus
    from word2vec_tpu.models.params import init_params
    from word2vec_tpu.ops.tables import DeviceTables
    from word2vec_tpu.ops.train_step import jit_train_step
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model=args.model, train_method="ns", negative=args.negative,
        word_dim=args.dim, window=args.window, subsample_threshold=1e-4,
        batch_rows=args.rows, max_sentence_len=args.len,
    )
    vocab = zipf_vocab(args.vocab, 17_000_000)
    ids = zipf_corpus_ids(vocab, 600_000, seed=0)
    corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
    tables = DeviceTables.build(vocab, cfg)
    step = jit_train_step(cfg, tables)
    params = init_params(cfg, len(vocab), jax.random.key(0))
    batcher = BatchIterator(corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1)
    alpha = jnp.float32(cfg.init_alpha)
    key = jax.random.key(7)
    tok0 = jnp.asarray(next(batcher.epoch())[0])
    for i in range(3):
        params, _ = step(params, tok0, jax.random.fold_in(key, i), alpha)
    jax.block_until_ready(params)

    jax.profiler.start_trace(args.out)
    for i in range(args.steps):
        params, _ = step(params, tok0, jax.random.fold_in(key, 10 + i), alpha)
    jax.block_until_ready(params)
    jax.profiler.stop_trace()
    print(f"trace written to {args.out} ({args.steps} steps, "
          f"device={jax.devices()[0].device_kind})")
    return args.out


def report(trace_dir: str, top: int = 30) -> None:
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

    files = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb")
    ))
    if not files:
        raise SystemExit(f"no xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "gpu" not in plane.name.lower():
            continue
        print(f"PLANE: {plane.name}")
        ev_meta = plane.event_metadata
        agg: collections.Counter = collections.Counter()
        cnt: collections.Counter = collections.Counter()
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                agg[name] += ev.duration_ps / 1e12
                cnt[name] += 1
        total = sum(agg.values())
        print(f"  XLA Ops total: {total * 1e3:.2f} ms")
        copies = sum(d for n, d in agg.items() if n.startswith("%copy"))
        print(f"  layout copies: {copies * 1e3:.2f} ms "
              f"({100 * copies / max(total, 1e-12):.1f}%)")
        for name, d in agg.most_common(top):
            print(f"    {d * 1e3:9.3f} ms x{cnt[name]:<4d} {name[:110]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    cap = sub.add_parser("capture")
    cap.add_argument("--steps", type=int, default=10)
    cap.add_argument("--dim", type=int, default=300)
    cap.add_argument("--window", type=int, default=5)
    cap.add_argument("--negative", type=int, default=5)
    cap.add_argument("--rows", type=int, default=256)
    cap.add_argument("--len", type=int, default=192)
    cap.add_argument("--vocab", type=int, default=71000)
    cap.add_argument("--model", choices=["sg", "cbow"], default="sg")
    cap.add_argument("--out", default="/tmp/w2vtrace")
    rep = sub.add_parser("report")
    rep.add_argument("trace_dir")
    rep.add_argument("--top", type=int, default=30)
    args = ap.parse_args()
    if args.cmd == "capture":
        report(capture(args))
    else:
        report(args.trace_dir, args.top)


if __name__ == "__main__":
    main()
