#!/usr/bin/env python
"""On-device op-level profiling: capture a jax.profiler trace of the train
step and print the TPU op breakdown (time per fused op, copies, scatters).

The tensorboard-plugin-profile converter in this image is broken
(protobuf/_pywrap mismatch), so the xplane.pb is parsed directly with the
tensorflow.tsl protobuf bindings. Requires
PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python (set automatically below).

Usage:
  python benchmarks/trace_tools.py capture [--steps 10] [--dim 300] ...
  python benchmarks/trace_tools.py report /tmp/w2vtrace

`capture` traces the flagship band-kernel step on whatever device JAX
resolves and then reports. Use `report` on an existing trace directory.
The main diagnostic use: find layout copies (%copy.* on [B, L, d]) and
scatter fusions worth restructuring (VERDICT r1 item "what's weak" 4).
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import sys

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def capture(args) -> str:
    """Trace the path bench.py actually times.

    By default that is the device-resident chunked runner (ops/resident.py
    — the banked 30.39x default, TPU_R4/default.json), NOT the per-step
    dispatch the round-2 trace profiled; the round-4 verdict flagged that
    staleness ("weak" item 2). --resident 0 falls back to the old per-step
    capture for comparison. Lever flags mirror bench.py so any queued
    config (pallas backend, neg-scope, bf16 tables...) can be profiled.
    """
    import json as _json

    import jax

    if args.cpu:
        # the axon sitecustomize overrides the JAX_PLATFORMS env var; a
        # config.update after import wins over both (same trick as bench.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import BatchIterator, PackedCorpus
    from word2vec_tpu.models.params import init_params
    from word2vec_tpu.ops.tables import DeviceTables
    from word2vec_tpu.ops.train_step import jit_train_step
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model=args.model, train_method="ns", negative=args.negative,
        word_dim=args.dim, window=args.window, subsample_threshold=1e-4,
        batch_rows=args.rows, max_sentence_len=args.len,
        band_backend=args.band_backend,
        negative_scope=args.neg_scope, shared_negatives=args.kp,
        fused_tables=bool(args.fused), dtype=args.table_dtype,
        stochastic_rounding=bool(args.sr),
    )
    vocab = zipf_vocab(args.vocab, 17_000_000)
    ids = zipf_corpus_ids(vocab, args.tokens, seed=0)
    corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
    tables = DeviceTables.build(vocab, cfg)
    params = init_params(cfg, len(vocab), jax.random.key(0))
    key = jax.random.key(7)

    if args.resident:
        from word2vec_tpu.ops import resident as res

        batcher = BatchIterator(
            corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1
        )
        S, _ = cfg.chunk_geometry(
            batcher.steps_per_epoch(), cap=args.chunk_cap
        )
        alphas = jnp.full((S,), cfg.init_alpha, jnp.float32)
        chunk_fn = res.jit_resident_chunk_runner(cfg, tables)
        order = res.epoch_order(1, 0, corpus.num_rows)
        corpus_dev = res.device_corpus(corpus)
        order_dev = jnp.asarray(order.astype(np.int32))
        params, _ = chunk_fn(  # warmup / compile
            params, corpus_dev, order_dev, key, 0, 0, alphas
        )
        jax.block_until_ready(params)

        steps = S * args.chunks
        jax.profiler.start_trace(args.out)
        for c in range(args.chunks):
            params, _ = chunk_fn(
                params, corpus_dev, order_dev, key, c * S, c * S, alphas
            )
        jax.block_until_ready(params)
        jax.profiler.stop_trace()
        shape = f"{args.chunks} chunks x S={S}"
    else:
        step = jit_train_step(cfg, tables)
        batcher = BatchIterator(
            corpus, cfg.batch_rows, cfg.max_sentence_len, seed=1
        )
        alpha = jnp.float32(cfg.init_alpha)
        tok0 = jnp.asarray(next(batcher.epoch())[0])
        for i in range(3):
            params, _ = step(params, tok0, jax.random.fold_in(key, i), alpha)
        jax.block_until_ready(params)

        steps = args.steps
        jax.profiler.start_trace(args.out)
        for i in range(args.steps):
            params, _ = step(params, tok0, jax.random.fold_in(key, 10 + i), alpha)
        jax.block_until_ready(params)
        jax.profiler.stop_trace()
        shape = f"{steps} per-step dispatches"

    with open(os.path.join(args.out, "meta.json"), "w") as f:
        _json.dump({
            "steps": steps, "rows": args.rows, "len": args.len,
            "resident": bool(args.resident), "shape": shape,
            "device": jax.devices()[0].device_kind,
            "config": {
                "band_backend": args.band_backend,
                "neg_scope": args.neg_scope, "kp": args.kp,
                "fused": args.fused, "table_dtype": args.table_dtype,
            },
        }, f)
    print(f"trace written to {args.out} ({shape}, "
          f"device={jax.devices()[0].device_kind})")
    return args.out


def report(trace_dir: str, top: int = 30) -> None:
    import json as _json

    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: E402

    meta = None
    meta_path = os.path.join(trace_dir, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = _json.load(f)

    files = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.xplane.pb")
    ))
    if not files:
        raise SystemExit(f"no xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    for plane in xs.planes:
        if "TPU" not in plane.name and "gpu" not in plane.name.lower():
            continue
        print(f"PLANE: {plane.name}")
        ev_meta = plane.event_metadata
        agg: collections.Counter = collections.Counter()
        cnt: collections.Counter = collections.Counter()
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                agg[name] += ev.duration_ps / 1e12
                cnt[name] += 1
        total = sum(agg.values())
        print(f"  XLA Ops total: {total * 1e3:.2f} ms")
        if meta:
            print(f"  capture shape: {meta['shape']} "
                  f"(rows={meta['rows']}, len={meta['len']}, "
                  f"config={meta['config']})")
            print(f"  per optimizer step: "
                  f"{total * 1e3 / max(meta['steps'], 1):.3f} ms "
                  f"over {meta['steps']} steps")
        copies = sum(d for n, d in agg.items() if n.startswith("%copy"))
        print(f"  layout copies: {copies * 1e3:.2f} ms "
              f"({100 * copies / max(total, 1e-12):.1f}%)")
        for name, d in agg.most_common(top):
            print(f"    {d * 1e3:9.3f} ms x{cnt[name]:<4d} {name[:110]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    cap = sub.add_parser("capture")
    cap.add_argument("--steps", type=int, default=10,
                     help="per-step dispatches to trace (--resident 0 only)")
    cap.add_argument("--chunks", type=int, default=2,
                     help="chunk dispatches to trace (resident path)")
    cap.add_argument("--dim", type=int, default=300)
    cap.add_argument("--window", type=int, default=5)
    cap.add_argument("--negative", type=int, default=5)
    cap.add_argument("--rows", type=int, default=256)
    cap.add_argument("--len", type=int, default=192)
    cap.add_argument("--vocab", type=int, default=71000)
    cap.add_argument("--tokens", type=int, default=2_000_000,
                     help="synthetic corpus size for the capture")
    cap.add_argument("--model", choices=["sg", "cbow"], default="sg")
    cap.add_argument("--resident", type=int, default=1, choices=[0, 1],
                     help="trace the resident chunked runner (the bench "
                     "default) vs the old per-step dispatch")
    cap.add_argument("--chunk-cap", type=int, default=32)
    cap.add_argument("--band-backend", choices=["xla", "pallas"],
                     default="xla")
    cap.add_argument("--neg-scope", choices=["row", "batch"], default="row")
    cap.add_argument("--kp", type=int, default=64)
    cap.add_argument("--fused", type=int, default=0, choices=[0, 1])
    cap.add_argument("--table-dtype", choices=["float32", "bfloat16"],
                     default="float32")
    cap.add_argument("--sr", type=int, default=0, choices=[0, 1])
    cap.add_argument("--cpu", action="store_true",
                     help="force the CPU backend (the sitecustomize "
                     "overrides JAX_PLATFORMS; this wins)")
    cap.add_argument("--out", default="/tmp/w2vtrace")
    rep = sub.add_parser("report")
    rep.add_argument("trace_dir")
    rep.add_argument("--top", type=int, default=30)
    args = ap.parse_args()
    if args.cmd == "capture":
        report(capture(args))
    else:
        report(args.trace_dir, args.top)


if __name__ == "__main__":
    main()
