# Shared machinery for the per-round TPU measurement queues.
# Source from a round script after setting OUT (banking dir), e.g.:
#   OUT=benchmarks/TPU_R4
#   . "$(dirname "$0")/tpu_queue_lib.sh"
# Provides: probe, wait_for_chip, run_item, run_trace, and a flock
# single-instance guard so a second queue launch exits instead of racing the
# first on the one TPU chip (two concurrent benches would contend for the
# chip and could bank contention-degraded numbers as official evidence).

mkdir -p "$OUT"
LOG=$OUT/queue.log

# Single-instance guard, keyed on the CHIP (benchmarks/.tpu.lock), not the
# round dir: two different rounds' queues would contend for the same one TPU
# just as hard as two copies of the same round. Held on fd 9 for the queue's
# lifetime; children are spawned with 9>&- so a hung orphaned bench cannot
# keep the lock after the queue itself is killed.
exec 9>"benchmarks/.tpu.lock"
if ! flock -n 9; then
  echo "$(date -u +%FT%TZ) second instance pid=$$ refused (chip lock held)" >> "$LOG"
  exit 0
fi

echo "$(date -u +%FT%TZ) queue started pid=$$" >> "$LOG"

# Per-OPERATION chip lock, distinct from the lifetime instance guard above:
# held only while something actually touches the TPU (a probe, one bench
# item, a trace). bench.py acquires the same lock with a bounded wait when
# invoked OUTSIDE the queue (the round-end driver run), so the official
# BENCH artifact never races a queue item on the one chip — and the queue's
# probes block while such a run holds it, instead of perturbing it.
CHIP=benchmarks/.chip.lock

# -k 10: the axon tunnel's failure mode is a HANG in an uninterruptible read;
# without a kill-after, `timeout`'s SIGTERM is ignored and the queue (and its
# heartbeat) wedges behind the child forever.
# 60 s probe budget: a LIVE tunnel initializes the backend in ~5-15 s
# (measured; first-compile cost comes later, not at init), so 60 s only
# bounds the hang case — and with the 50 s sleep below the dead-tunnel
# detection cycle is ~2 min instead of ~3.5, which matters when the
# tunnel surfaces for short windows (round 4's was 17 minutes total).
probe() { flock -w 3600 "$CHIP" timeout -k 10 60 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1 9>&-; }

# Heartbeat cadence: a failed-probe iteration normally costs up to 85 s
# (probe timeout+kill on a hung tunnel) + 110 s sleep ~= 195 s, so
# HEARTBEAT_EVERY=20 logs one line per ~65 min of dead tunnel (worst case;
# ~40 min if probes fail fast). A probe can also block on the chip lock
# behind an outside bench run (up to 3600 s), so a failed probe means
# "tunnel down OR chip busy elsewhere" — the heartbeat says so.
HEARTBEAT_EVERY=${HEARTBEAT_EVERY:-20}
FAILED_PROBES=0
wait_for_chip() {
  local waited=0
  until probe; do
    FAILED_PROBES=$((FAILED_PROBES + 1)); waited=$((waited + 1))
    if [ $((FAILED_PROBES % HEARTBEAT_EVERY)) -eq 0 ]; then
      echo "$(date -u +%FT%TZ) heartbeat: $FAILED_PROBES probes failed so far (tunnel down or chip held elsewhere)" >> "$LOG"
    fi
    sleep 50 9>&-
  done
  [ "$waited" -gt 0 ] && echo "$(date -u +%FT%TZ) chip live after $waited failed probes" >> "$LOG"
}

# run_item <name> <timeout_s> <success_marker> <cmd...>
# Banks the last stdout line to $OUT/<name>.json iff it contains the marker
# AND parses as JSON (a timeout mid-write must not bank a truncated line that
# then blocks the item from ever retrying); otherwise saves it as .failed so
# a later restart retries the item.
run_item() {
  local name=$1 tmo=$2 marker=$3; shift 3
  [ -s "$OUT/$name.json" ] && return 0
  wait_for_chip
  echo "$(date -u +%FT%TZ) start $name: $*" >> "$LOG"
  # Chip lock on fd 8, held by THIS shell for the item's duration (closed
  # for children like fd 9). The wait covers a full outside bench run
  # (bench.py holds the lock until exit, run-timeout 3600 s) with slack; a
  # timeout leaves the item UNBANKED (no .failed) so the next queue
  # launch retries it, and logs the distinct reason.
  exec 8>"$CHIP"
  if ! flock -w 4500 8; then
    echo "$(date -u +%FT%TZ) chip lock busy >4500s; leaving $name for retry" >> "$LOG"
    exec 8>&-
    return 0
  fi
  # the 9>&- 8>&- covers the whole pipeline group: tail must not inherit
  # the lock fds, or a wedged bench holding the pipe keeps tail (and the
  # locks) alive after the queue itself is killed. W2V_CHIP_LOCK_HELD
  # tells the item's own bench.py not to re-acquire the chip lock its
  # parent already holds.
  { W2V_CHIP_LOCK_HELD=1 timeout -k 10 "$tmo" "$@" 2>>"$OUT/$name.stderr" \
      | tail -1 > "$OUT/$name.tmp"; } 9>&- 8>&-
  exec 8>&-
  if grep -q "$marker" "$OUT/$name.tmp" 2>/dev/null \
     && python -c "import json,sys; json.loads(sys.stdin.read())" < "$OUT/$name.tmp" 2>/dev/null; then
    mv "$OUT/$name.tmp" "$OUT/$name.json"
    rm -f "$OUT/$name.stderr" "$OUT/$name.failed"
    echo "$(date -u +%FT%TZ) banked $name: $(cat "$OUT/$name.json")" >> "$LOG"
  else
    mv "$OUT/$name.tmp" "$OUT/$name.failed" 2>/dev/null
    echo "$(date -u +%FT%TZ) FAILED $name" >> "$LOG"
  fi
}

# run_trace <tmpdir>
# Captures a profiler trace and banks the parsed report to
# $OUT/trace_report.txt iff it contains a device plane ("XLA Ops total"), so
# a failed capture is retried on the next restart instead of banking a
# traceback.
run_trace() {
  local tmpdir=$1
  [ -s "$OUT/trace_report.txt" ] && return 0
  wait_for_chip
  echo "$(date -u +%FT%TZ) start trace" >> "$LOG"
  flock -w 4500 "$CHIP" timeout -k 10 900 \
    python benchmarks/trace_tools.py capture --out "$tmpdir" \
    >> "$OUT/trace_capture.out" 2>&1 9>&-
  timeout -k 10 300 python benchmarks/trace_tools.py report "$tmpdir" \
    > "$OUT/trace_report.tmp" 2>&1 9>&-
  if grep -q "XLA Ops total" "$OUT/trace_report.tmp"; then
    mv "$OUT/trace_report.tmp" "$OUT/trace_report.txt"
    echo "$(date -u +%FT%TZ) banked trace_report" >> "$LOG"
  else
    echo "$(date -u +%FT%TZ) FAILED trace" >> "$LOG"
  fi
}
