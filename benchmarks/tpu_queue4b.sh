#!/bin/bash
# Round-4 follow-up measurement queue — runs AFTER tpu_queue4.sh (the
# chip flock in tpu_queue_lib.sh serializes them: launched while queue4
# holds the lock this script just exits; benchmarks/tpu_supervisor4.sh
# keeps re-launching it until every run_item here has a banked JSON in
# benchmarks/TPU_R4/ — the COMPLETE log lines are informational only).
#
# Items here are the levers invented or re-designed mid-round plus the
# combo escalations that depend on the queue4 singles:
#   - slab_sorted: slab-space context scatter v2 — r2 measured the
#     UNSORTED slab scatter losing (2.26M vs 3.64M w/s); v2 argsorts the
#     slab ids so the scatter keeps XLA's sorted fast path while still
#     skipping the overlap-add layout-copy chain (band_step.py).
#   - b1024/c192: batch-rows and chunk-cap escalation beyond the queue4
#     sweep points.
#   - combo items: stack the individually-promising levers.
#   - full_stack retry LAST with a longer cap: its first attempt wedged
#     >900s (compile) and the kill coincided with a tunnel outage.
#
# Usage: nohup bash benchmarks/tpu_queue4b.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/TPU_R4
. benchmarks/tpu_queue_lib.sh

B='python bench.py --probe-retries 1'
TPU='"platform": "tpu"'

# --- new/re-designed levers --------------------------------------------------
#   - pallas: the fused VMEM-resident band kernel (ops/pallas_band.py) —
#     replaces the whole matmul/copy/elementwise middle of the step, the
#     segment the round-2 trace put at ~4.7 of 7.97 ms.
# Two-tier hs update (config.hs_dense_top, built this round): dense-matmul
# top-P tier + compacted tail scatter — A/B vs queue4's one-tier hs_dim200.
# Early in the list: it is a brand-new lever with a ~3x step-time model
# behind it (PERF.md "two-tier hs"), so its first on-chip number decides
# whether to promote it for the hs configs.
run_item hs_dim200_dense512   900 "$TPU" $B --train-method hs --dim 200 --hs-dense-top 512
run_item hs_dim200_dense1024  900 "$TPU" $B --train-method hs --dim 200 --hs-dense-top 1024
run_item pallas               900 "$TPU" $B --band-backend pallas
run_item slab_sorted          900 "$TPU" $B --slab-scatter 1
run_item b1024                900 "$TPU" $B --batch-rows 1024
# b512 measured BELOW default-256 (27.2x vs 30.4x): the optimum may sit
# under 256 — sweep downward too
run_item b128                 900 "$TPU" $B --batch-rows 128
run_item b192                 900 "$TPU" $B --batch-rows 192
run_item c192                 900 "$TPU" $B --chunk-cap 192
run_item pallas_c96           900 "$TPU" $B --band-backend pallas --chunk-cap 96
run_item pallas_b512          900 "$TPU" $B --band-backend pallas --batch-rows 512
run_item pallas_b512_c96      900 "$TPU" $B --band-backend pallas --batch-rows 512 --chunk-cap 96
# BASELINE config 2 (cbow dim=100) through the fused kernel's cbow branch
run_item cbow_dim100_pallas   900 "$TPU" $B --model cbow --dim 100 --band-backend pallas
# bf16 tables + SR through the kernel: pallas shrinks the step's middle,
# bf16 halves the gather/scatter edges that remain outside it
run_item pallas_bf16sr        900 "$TPU" $B --band-backend pallas --table-dtype bfloat16 --sr 1
run_item pallas_bf16sr_b512   900 "$TPU" $B --band-backend pallas --table-dtype bfloat16 --sr 1 --batch-rows 512
# batch-scoped negatives through the kernel (NB=1 block sharing): one
# [KP,d] negative block revisited across the whole grid
run_item pallas_negbatch      900 "$TPU" $B --band-backend pallas --neg-scope batch --kp 256

# --- combos over queue4 singles ---------------------------------------------
run_item b512_c96             900 "$TPU" $B --batch-rows 512 --chunk-cap 96
run_item slab_b512            900 "$TPU" $B --slab-scatter 1 --batch-rows 512
run_item negbatch_b512        900 "$TPU" $B --neg-scope batch --kp 256 --batch-rows 512
run_item bf16sr_negbatch      900 "$TPU" $B --table-dtype bfloat16 --sr 1 --neg-scope batch --kp 256
run_item slab_rbg_b512        900 "$TPU" $B --slab-scatter 1 --prng rbg --batch-rows 512

# on-chip at-scale quality of the two-tier hs update (CPU row in
# QUALITY_FULL_r4_cpu.txt; this is the on-chip counterpart)
run_item quality_hs_dense512 2400 "$TPU" \
  python benchmarks/quality_full.py --tokens 4000000 --train-method hs --dim 300 --hs-dense-top 512

# --- deferred retry: wedged once at 900s, tunnel died around the kill --------
run_item full_stack          1800 "$TPU" $B --fused 1 --chunk-cap 96 --neg-scope batch --kp 256 --table-dtype bfloat16 --sr 1

echo "$(date -u +%FT%TZ) QUEUE4B COMPLETE after $FAILED_PROBES failed probes total" >> "$LOG"
