#!/bin/bash
# Round-5 full-budget accuracy-parity matrix vs the compiled C++ reference.
# Same rows as r4 plus the NEW graded-similarity rows: Spearman vs
# unique-rank golds (no tie ceiling — VERDICT r4 weak item 5), which now
# discriminates where the old two-level golds pinned every artifact at
# 0.866. The hs dense-top multi-corpus replication lives in its own
# artifact (hs_dense_parity_r5.sh -> PARITY_HS_DENSE_r5.jsonl).
# Usage: bash benchmarks/parity_matrix5.sh > benchmarks/PARITY_MATRIX_r5.txt
cd "$(dirname "$0")/.." || exit 1
P="python benchmarks/parity.py --tokens 200000 --dim 64 --iters 5"
echo "# Parity matrix r5 ($(date -u +%F)): ours vs compiled reference,"
echo "# same stream, same eval. delta_* = ours - reference."
for args in \
  "--model sg   --train-method ns" \
  "--model cbow --train-method ns" \
  "--model sg   --train-method hs" \
  "--model sg   --train-method hs --hs-dense-top 512" \
  "--model cbow --train-method hs" \
  "--model sg   --train-method ns --kernel pair" \
  "--model sg   --train-method ns --prng rbg" \
  "--model sg   --train-method ns --table-dtype bfloat16 --sr 1" \
  "--model sg   --train-method ns --negative-scope batch --shared-negatives 256" \
  ; do
  echo "## parity $args"
  timeout 1800 $P $args 2>/dev/null | tail -1
done
echo "## graded-similarity parity (unique-rank golds; tokens=240k)"
for args in \
  "--model sg   --train-method ns" \
  "--model cbow --train-method ns" \
  "--model sg   --train-method hs" \
  "--model sg   --train-method hs --hs-dense-top 512" \
  "--model sg   --train-method ns --negative-scope batch --shared-negatives 256" \
  ; do
  echo "## graded $args"
  timeout 1800 python benchmarks/parity.py --graded --tokens 240000 --dim 64 \
    --iters 5 --min-count 1 $args 2>/dev/null | tail -1
done
echo "## analogy parity (grid corpus, 3CosAdd)"
timeout 1800 python benchmarks/parity.py --analogy --tokens 300000 2>/dev/null | tail -1
echo "## analogy parity, cbow"
timeout 1800 python benchmarks/parity.py --analogy --tokens 300000 --model cbow 2>/dev/null | tail -1
