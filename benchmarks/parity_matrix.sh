#!/bin/bash
# Full-budget accuracy-parity matrix vs the compiled C++ reference (CPU).
# Rows: every shipped model x objective route at the 200k/dim64/5-iter
# budget (deltas are meaningful there; the CI tests gate a reduced budget),
# the pair-kernel route, KP sensitivity, bf16+SR tables, and the
# analogy-parity rows (grid corpus, 3CosAdd, BASELINE gate's second half).
# Usage: bash benchmarks/parity_matrix.sh > benchmarks/PARITY_MATRIX_r3.txt
cd "$(dirname "$0")/.." || exit 1
P="python benchmarks/parity.py --tokens 200000 --dim 64 --iters 5"
echo "# Parity matrix r3 ($(date -u +%F)): ours vs compiled reference,"
echo "# same stream, same eval. delta_* = ours - reference."
for args in \
  "--model sg   --train-method ns" \
  "--model cbow --train-method ns" \
  "--model sg   --train-method hs" \
  "--model cbow --train-method hs" \
  "--model sg   --train-method ns --kernel pair" \
  "--model sg   --train-method ns --shared-negatives 32" \
  "--model sg   --train-method ns --shared-negatives 8" \
  "--model sg   --train-method ns --prng rbg" \
  "--model sg   --train-method ns --table-dtype bfloat16 --sr 1" \
  "--model sg   --train-method ns --negative-scope batch --shared-negatives 256" \
  ; do
  echo "## parity $args"
  timeout 900 $P $args 2>/dev/null | tail -1
done
echo "## analogy parity (grid corpus, 3CosAdd)"
timeout 900 python benchmarks/parity.py --analogy --tokens 300000 2>/dev/null | tail -1
echo "## analogy parity, cbow"
timeout 900 python benchmarks/parity.py --analogy --tokens 300000 --model cbow 2>/dev/null | tail -1
echo "## analogy parity, hs"
timeout 900 python benchmarks/parity.py --analogy --tokens 300000 --train-method hs 2>/dev/null | tail -1
