#!/bin/bash
# Round-7 TPU measurement queue — the unified-table-scatter round (ISSUE 7).
#
# The tunnel has been dead since round 5, so queues 5/7 coexist: this one is
# ordered so a SHORT window banks the decisions this round actually made.
#
#   Tier 1 — the A/B pair that decides the tentpole: default (split) vs
#            --table-layout unified at the banked 30.4× config. The cost
#            model predicts −1.03 ms of the ~8 ms step for unified (the
#            per-layout scatter-row term, tune/cost_model.SCATTER_SEC_PER_ROW
#            — two 49k-row sorted scatters collapse to one at doubled
#            width); CPU A/B evidence is in benchmarks/COST_ATTRIB_r7.
#   Tier 2 — the fresh trace of the REAL default path (resident chunked
#            runner) the ROADMAP says must bank before any projection is
#            trusted, PLUS --trace step-span exports of both layouts so
#            `python -m word2vec_tpu.obs.tracediff` attributes the
#            scatter-term delta from banked artifacts (PERF.md worked
#            example).
#   Tier 3 — the planner-candidate stacks this PR added: unified ×
#            {kp32, kp16, bf16sr}, unified × pallas_oa, and an --autotune
#            probe that must be free to pick any of them.
#
# Forwarding-audit markers (the r4 lesson, tpu_queue5.sh): an item banks
# ONLY a record whose realized plan carries the requested layout/width —
# bench.py's outer->inner re-exec once dropped a flag and banked the XLA
# path under a pallas label. The plan JSON now carries table_layout /
# shared_negatives / table_dtype / stochastic_rounding (TunePlan schema 2),
# so the banked JSON itself proves what ran. JSON key order within "plan"
# is the TunePlan field declaration order (dataclasses.asdict:
# ... shared_negatives, negative_scope, band_backend, table_layout,
# table_dtype, stochastic_rounding), and "platform" precedes "plan" in
# bench.py's record, so one basic-regex grep covers each marker.
#
# Usage: nohup bash benchmarks/tpu_queue7.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/TPU_R7
. benchmarks/tpu_queue_lib.sh

B='python bench.py --probe-retries 1'
TPU='"platform": "tpu"'
# realized-layout markers: "table_layout" rides inside the record's "plan"
UNI='"platform": "tpu".*"table_layout": "unified"'
UNI_KP32='"platform": "tpu".*"shared_negatives": 32.*"table_layout": "unified"'
UNI_KP16='"platform": "tpu".*"shared_negatives": 16.*"table_layout": "unified"'
UNI_BF16SR='"platform": "tpu".*"table_layout": "unified".*"table_dtype": "bfloat16".*"stochastic_rounding": true'
UNI_OA='"platform": "tpu".*"band_backend": "pallas_oa".*"table_layout": "unified"'

# --- tier 1: the layout A/B that decides the tentpole -------------------------
run_item default              900 "$TPU" $B
run_item unified              900 "$UNI" $B --table-layout unified

# --- tier 2: the real-default-path trace + layout tracediff artifacts ---------
# run_trace banks the xprof decomposition of the resident chunked runner at
# the banked 30.4x config (ROADMAP open item 2a: no projection is
# trustworthy until this banks).
run_trace /tmp/tr_r7
# step-span exports for tracediff (obs/trace.py; diffing these attributes
# the scatter-term delta between layouts — PERF.md worked example):
run_item default_tracedump    900 "$TPU" $B --trace benchmarks/TPU_R7/trace_split
run_item unified_tracedump    900 "$UNI" $B --table-layout unified --trace benchmarks/TPU_R7/trace_unified

# --- tier 3: the new planner-candidate stacks ---------------------------------
# unified x KP width (ROADMAP lever c: KP=64->32/16 halves the negative
# einsum width each step; accuracy fence measured holding to KP=8):
run_item unified_kp32         900 "$UNI_KP32" $B --table-layout unified --kp 32
run_item unified_kp16         900 "$UNI_KP16" $B --table-layout unified --kp 16
# unified x bf16+SR (ROADMAP lever d: halves table bytes; SR keeps updates
# unbiased on the destination ulp grid; margin-neutral PARITY_MATRIX_r3):
run_item unified_bf16sr       900 "$UNI_BF16SR" $B --table-layout unified --table-dtype bfloat16 --sr 1
# unified x the overlap-add kernel (the only Pallas backend that composes
# with fused/unified tables — ops/pallas_overlap.py):
run_item unified_pallas_oa    900 "$UNI_OA" $B --table-layout unified --band-backend pallas_oa
# split-side KP singles for like-for-like attribution of the stacks above:
run_item kp16                 900 "$TPU" $B --kp 16
# the full stack the cost model ranks best at this shape:
run_item unified_kp32_bf16sr  900 "$UNI_KP32" $B --table-layout unified --kp 32 --table-dtype bfloat16 --sr 1
# the planner's own verdict (probe mode persists the winner in the plan
# cache; the banked record carries plan_probes for the audit trail):
run_item autotune_probe      1800 "$TPU" $B --autotune probe

echo "$(date -u +%FT%TZ) QUEUE7 COMPLETE after $FAILED_PROBES failed probes total" >> "$LOG"
