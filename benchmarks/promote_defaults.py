#!/usr/bin/env python
"""Decide lever defaults from banked evidence (VERDICT r3 item 6).

Reads every banked on-chip bench record (benchmarks/TPU_R*/{name}.json)
AND the round's full-budget parity matrix, then prints one decision line
per lever: best banked words/sec vs the default config's, the lever's
parity delta_margin vs the compiled reference, and a verdict. The
PROMOTION RULE is mechanical and recorded here so a human (or the next
round's builder) applies it rather than re-litigating:

  promote a lever to default iff
    (a) its banked on-chip words/sec >= the default config's on the SAME
        metric/corpus scale (throughput not worse), AND
    (b) its quality evidence shows it does not move training outcomes:
        - ns levers: full-budget parity delta_margin vs the reference
          within the calibrated +-0.02 noise band (two-sided; calibration:
          benchmarks/PARITY_CALIB_r4.jsonl). A delta OUTSIDE the band in
          EITHER direction blocks promotion until it is explained by a
          matched-baseline comparison (below) — r4's asymmetric
          "or positive" acceptance is retired: a positive delta means the
          lever changes dynamics, which is exactly what needs explaining.
        - the hs dense-top lever: the MATCHED comparison — ours(dense)
          vs ours(one-tier) on the same corpus — must sit within the
          band. Measured r5: <= 0.0003 on 4 structurally different
          corpora (PARITY_HS_DENSE_r5.jsonl), i.e. the lever is
          margin-NEUTRAL; the +0.031..+0.042 ours-vs-reference delta that
          triggered VERDICT r4 weak item 3 replicates IDENTICALLY in the
          one-tier baseline, so it is a kernel-family offset (our batched
          hs converges slightly above the reference's Hogwild hs at this
          budget), not a lever effect.
        - batch-scoped negatives: matched comparison ours(negbatch) vs
          ours(row-scope) measured +0.017..+0.030 on all three r5 corpus
          structures (PARITY_NEGBATCH_r5.jsonl) — a REAL, direction-stable
          quality improvement (lower per-center gradient variance), so
          the lever promotes under "never worse than its own baseline on
          any measured structure". This is the documented form of the
          positive-side exception r4's verdict demanded evidence for.
        AND
    (c) it needs no route/scope restriction a default must not have
        (e.g. band_backend=pallas is single-chip only, so it can be the
        BENCH default but not the library default).

Usage: python benchmarks/promote_defaults.py
"""

from __future__ import annotations

import glob
import json
import os
import re

HERE = os.path.dirname(os.path.abspath(__file__))
NOISE = 0.02  # calibrated reference run-to-run band (PARITY_CALIB_r4.jsonl)

# lever item name -> (config substrings identifying its PARITY_MATRIX_r4
# row, library-default eligibility note). Substrings match the matrix's
# self-describing config field (backend/scope/dtype/sr/slab).
LEVERS = {
    "pallas": (("backend=pallas", "scope=row", "dtype=float32"),
               "bench default only (single-chip; sharded trainers reject)"),
    "pallas_b512": (("backend=pallas", "scope=row", "dtype=float32"),
                    "bench default only (single-chip)"),
    "pallas_c96": (("backend=pallas", "scope=row", "dtype=float32"),
                   "bench default only (single-chip)"),
    "pallas_b512_c96": (("backend=pallas", "scope=row", "dtype=float32"),
                        "bench default only (single-chip)"),
    "pallas_bf16sr": (("backend=pallas", "dtype=bfloat16", "sr=1"),
                      "bench default only (single-chip)"),
    "pallas_bf16sr_b512": (("backend=pallas", "dtype=bfloat16", "sr=1"),
                           "bench default only (single-chip)"),
    "pallas_negbatch": (("backend=pallas", "scope=batch"),
                        "bench default only (single-chip)"),
    "slab_sorted": (("backend=xla", "slab=1"),
                    "library-eligible (all band routes)"),
    "b512": (None, "library-eligible (geometry; parity-invariant)"),
    "b1024": (None, "library-eligible (geometry; parity-invariant)"),
    "chunk96": (None, "library-eligible (dispatch; trajectory-identical)"),
    "c192": (None, "library-eligible (dispatch; trajectory-identical)"),
    "b512_c96": (None, "library-eligible (geometry+dispatch)"),
    "rbg": (None, "library-eligible (prng; r3 matrix delta +0.0081)"),
    "negbatch_kp256": (("backend=xla", "scope=batch"),
                       "library-eligible (quality-positive every budget)"),
    "bf16sr": (("backend=xla", "dtype=bfloat16", "sr=1"),
               "library-eligible (margin-neutral)"),
    "fused": (None, "library-eligible (ns band only; bitwise-identical)"),
    "kp32": (None, "library-eligible (r3 matrix delta +0.0139)"),
    "b128": (None, "library-eligible (geometry; parity-invariant)"),
    "b192": (None, "library-eligible (geometry; parity-invariant)"),
    "hs_dim200_dense512": (
        None, "library-eligible for hs (one-tier-exact semantics, "
        "tests/test_hs_dense.py; at-scale quality: QUALITY_FULL_r4 rows)"),
    "hs_dim200_dense1024": (
        None, "library-eligible for hs (one-tier-exact semantics)"),
}

# Each un-levered config item defines the words/sec bar for ITS metric;
# every lever item is compared against the bar sharing its metric string
# (hs_dim200_dense512 vs hs_dim200, etc.). "default" is the flagship bar.
BASE_ITEMS = ("default", "hs_dim200", "cbow_dim100", "sg_w10")


def load_parity_rows() -> list:
    """Rows from the full-budget parity matrices, NEWEST FIRST (r5
    supersedes r4: same config strings, refreshed reference training, plus
    the graded rows). parity_delta takes the first matching row, so a
    config present in both resolves to r5, while configs the in-progress
    r5 run hasn't reached yet still resolve to their r4 row."""
    rows = []
    for name in ("PARITY_MATRIX_r5.txt", "PARITY_MATRIX_r4.txt"):
        path = os.path.join(HERE, name)
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line.startswith("{"):
                        try:
                            rows.append(json.loads(line))
                        except json.JSONDecodeError:
                            pass
        except OSError:
            continue
    return rows


def parity_delta(rows: list, selectors) -> float | None:
    if selectors is None:
        return None
    for r in rows:
        cfg = r.get("config", "")
        if all(s in cfg for s in selectors) and "delta_margin" in r:
            return r["delta_margin"]
    return None


def _matched_margins(filename: str, classify) -> list:
    """Shared reader for the matched-baseline artifacts: pair each
    corpus's lever/base ours.cos_margin rows and return the list of
    (lever - base) deltas. `classify(config_str)` returns "lever",
    "base", or None (row ignored — misfiling a foreign row as a baseline
    would silently corrupt the deltas, so classifiers must be strict)."""
    by_corpus: dict = {}
    try:
        with open(os.path.join(HERE, filename)) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                m = r.get("ours", {}).get("cos_margin")
                if m is None:
                    continue
                tier = classify(r.get("config", ""))
                if tier is None:
                    continue
                by_corpus.setdefault(r.get("corpus"), {})[tier] = m
    except OSError:
        return []
    return [
        t["lever"] - t["base"]
        for t in by_corpus.values() if "lever" in t and "base" in t
    ]


def hs_dense_matched_delta(p: int = 512) -> float | None:
    """Max |ours(dense-top=p) - ours(one-tier)| cos_margin across the
    matched corpus pairs of PARITY_HS_DENSE_r5.jsonl — the controlled
    comparison that isolates the dense-top lever's own effect from the hs
    kernel-family ours-vs-reference offset (r5; VERDICT r4 weak item 3).

    Evidence is PER TIER SIZE: rows with a different dense-top value are
    ignored (not misfiled as baselines), and a tier size with no rows
    returns None — the caller must HOLD promotion rather than borrow
    another tier's evidence."""
    def classify(cfg: str):
        match = re.search(r"dense-top=(\d+)", cfg)
        top = int(match.group(1)) if match else 0
        if top == 0:
            return "base"
        if top == p:
            return "lever"
        return None

    deltas = _matched_margins("PARITY_HS_DENSE_r5.jsonl", classify)
    return max(abs(d) for d in deltas) if deltas else None


def negbatch_matched_delta() -> tuple | None:
    """(min, max) of ours(negbatch) - ours(row-scope) cos_margin across the
    matched corpus pairs of PARITY_NEGBATCH_r5.jsonl. Unlike the hs
    dense-top lever (margin-neutral), batch-scoped negatives genuinely
    move the margin: +0.017..+0.030 on all three r5 corpus structures —
    consistent in direction, mechanism understood (one KP=256 pool per
    batch has lower per-center gradient variance than per-row KP=64
    pools). Promotion therefore allows it under the matched rule: never
    worse than its own baseline on any measured structure.

    The classifier pins the exact study configs — XLA backend, f32,
    scope=batch@kp256 vs scope=row@kp64 — so rows from any future sweep
    appended to the file are ignored rather than misfiled."""
    def classify(cfg: str):
        if "backend=xla" not in cfg or "dtype=float32" not in cfg:
            return None
        if "scope=batch" in cfg and "kp=256" in cfg:
            return "lever"
        if "scope=row" in cfg and "kp=64" in cfg:
            return "base"
        return None

    deltas = _matched_margins("PARITY_NEGBATCH_r5.jsonl", classify)
    return (min(deltas), max(deltas)) if deltas else None


def main() -> None:
    records: dict = {}
    for path in sorted(glob.glob(os.path.join(HERE, "TPU_R*", "*.json"))):
        name = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path) as f:
                rec = json.loads(f.read().strip().splitlines()[-1])
        except (OSError, json.JSONDecodeError, IndexError):
            continue
        if rec.get("platform") != "tpu" or not isinstance(
            rec.get("value"), (int, float)
        ):
            continue
        key = (name, rec.get("metric"))
        if key not in records or rec["value"] > records[key]["value"]:
            records[key] = rec

    bars: dict = {}  # metric -> (bar item name, record)
    for bn in BASE_ITEMS:
        for (name, metric), rec in records.items():
            if name == bn and metric not in bars:
                bars[metric] = (bn, rec)
    if not bars:
        print("no banked on-chip un-levered config record — nothing to compare")
        return
    for metric, (bn, rec) in sorted(bars.items()):
        print(
            f"bar [{bn}]: {rec['value']:,.0f} words/sec "
            f"({rec.get('vs_baseline')}x baseline) on {metric!r}"
        )
    print()
    parity = load_parity_rows()
    nb = negbatch_matched_delta()  # loop-invariant; read the file once
    for (name, metric), rec in sorted(records.items()):
        if name in BASE_ITEMS:
            continue
        selectors, note = LEVERS.get(name, (None, "unclassified"))
        m_dense = re.match(r"hs_dim200_dense(\d+)$", name)
        if m_dense:
            # matched-baseline evidence (rule (b), hs dense-top branch) —
            # strictly per tier size: dense1024 must NOT ride dense512's
            # replication study
            dm = hs_dense_matched_delta(int(m_dense.group(1)))
            if dm is None:
                q = "no matched rows for this tier size"
                blocked = True
                note = (
                    f"HOLD: run hs_dense_parity with P={m_dense.group(1)} "
                    "before promoting"
                )
            else:
                q = (
                    f"matched |dense-onetier| margin {dm:.4f} "
                    + ("OK" if dm <= NOISE else "QUALITY-DIVERGENT")
                )
                blocked = dm > NOISE
        elif name in ("negbatch_kp256", "negbatch_b512") and nb is not None:
            # the matched study is XLA/f32-specific: combos that change the
            # kernel or dtype (pallas_negbatch, bf16sr_negbatch) keep their
            # own parity rows below — a pallas-kernel quality regression
            # must not ride the XLA evidence. b512 qualifies because batch
            # geometry is parity-invariant (measured r2-r4).
            lo, hi = nb
            q = (
                f"matched lever-base margin [{lo:+.4f}, {hi:+.4f}] "
                + ("OK (documented positive effect)" if lo >= -NOISE
                   else "QUALITY-DIVERGENT")
            )
            blocked = lo < -NOISE
        else:
            dm = parity_delta(parity, selectors)
            # two-sided band (rule (b)): a delta outside the band in
            # EITHER direction blocks — r4's "or positive" is retired
            q = (
                "no parity row" if dm is None
                else f"delta_margin {dm:+.4f} "
                + ("OK" if abs(dm) <= NOISE else "OUTSIDE-BAND")
            )
            blocked = dm is not None and abs(dm) > NOISE
        if metric not in bars:
            verdict = f"INCOMPARABLE (no bar for metric {metric!r})"
        else:
            bn, base = bars[metric]
            ratio = rec["value"] / base["value"]
            if ratio < 1.0:
                verdict = f"{ratio:5.2f}x {bn} -> KEEP default"
            elif blocked:
                verdict = f"{ratio:5.2f}x {bn} -> BLOCKED by quality"
            else:
                verdict = f"{ratio:5.2f}x {bn} -> PROMOTE ({note})"
        print(f"{name:22s} {rec['value']:>12,.0f} w/s  [{q}]  {verdict}")


if __name__ == "__main__":
    main()
