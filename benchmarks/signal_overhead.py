#!/usr/bin/env python
"""Measure the derived-signal plane's overhead on the CPU drill shape.

The signal-plane contract (obs/signals.py) is the same standing one as
trace/watchdog/quality before it: the per-boundary beat (`on_boundary`) is
one clock read + an integer compare off the window edge, with zero device
fetches; the window close (once per `window` steps) is host-side float math
plus one small row publish. This harness pins the <1% wall number instead
of a hope — the watchdog/trace A/B discipline: train the same synthetic
shape with the engine attached (window 50, an SLO rule, a fleet aggregator
writing rows+fleet.json into a temp metrics dir — the FULL production
wiring) and detached, alternating reps, median wall; then time one beat
against the run's own p50 step time.

One JSON line to stdout (bank as benchmarks/SIGNAL_OVERHEAD_cpu.json):
    python benchmarks/signal_overhead.py [--tokens 200000] [--reps 3]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=200_000)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--batch-rows", type=int, default=64)
    ap.add_argument("--window", type=int, default=50)
    args = ap.parse_args()

    import numpy as np

    import jax
    from word2vec_tpu.config import Word2VecConfig
    from word2vec_tpu.data.batcher import PackedCorpus
    from word2vec_tpu.obs.fleet import FleetAggregator
    from word2vec_tpu.obs.signals import SignalEngine
    from word2vec_tpu.obs.slo import SloEvaluator, parse_slo
    from word2vec_tpu.train import Trainer
    from word2vec_tpu.utils.synthetic import zipf_corpus_ids, zipf_vocab

    cfg = Word2VecConfig(
        model="sg", train_method="ns", negative=5, word_dim=args.dim,
        window=5, batch_rows=args.batch_rows, max_sentence_len=192,
        min_count=1, iters=1, seed=0,
        chunk_steps=1,  # per-step boundaries: the worst case for beat count
    )
    vocab = zipf_vocab(71000, 17_000_000)
    flat = np.concatenate(zipf_corpus_ids(vocab, args.tokens, seed=0))
    ids = [flat[i:i + 1000] for i in range(0, len(flat), 1000)]
    corpus = PackedCorpus.pack(ids, cfg.max_sentence_len)
    trainer = Trainer(cfg, vocab, corpus)
    tmp = tempfile.mkdtemp(prefix="w2v_signal_overhead_")

    def make_engine():
        return SignalEngine(
            window=args.window,
            phases=trainer.phases,
            flight=trainer.flight,
            metrics_dir=tmp,
            host=0,
            slo=SloEvaluator(
                parse_slo("throughput_wps<0.5*baseline:for=3")
            ),
            aggregator=FleetAggregator(tmp, window_steps=args.window),
        )

    def timed_run(wired: bool):
        trainer.signals = make_engine() if wired else None
        t0 = time.perf_counter()
        _, rep = trainer.train(state=trainer.init_state(), log_every=0)
        if trainer.signals is not None:
            trainer.signals.close()
        return time.perf_counter() - t0, rep

    timed_run(True)  # warmup: compile out of the measurement
    base_walls, wired_walls, steps, windows = [], [], 0, 0
    for i in range(args.reps):
        # ORDER-FAIR alternation: on this host the second run of any
        # back-to-back pair is systematically slower (allocator/frequency
        # drift), enough to swamp a sub-1% effect — measured both ways at
        # ±20% with a fixed order. Flipping which leg goes first per rep
        # cancels the bias instead of hoping it away.
        for wired in ((False, True) if i % 2 == 0 else (True, False)):
            w, rep = timed_run(wired)
            if wired:
                wired_walls.append(w)
                windows = (rep.signals or {}).get("windows", 0)
            else:
                base_walls.append(w)
                steps = rep.steps

    # per-beat microcost against the run's own step time (the only
    # per-boundary work; window closes amortize over `window` steps)
    _, rep = trainer.train(state=trainer.init_state(), log_every=0)
    step_durs_ms = sorted(
        e["dur"] / 1e3
        for e in trainer.flight.ring.events()
        if e.get("ph") == "X" and e["name"] == "step"
    )
    p50_step_ms = step_durs_ms[len(step_durs_ms) // 2]
    probe = SignalEngine(window=10_000_000)  # beat cost only, never closes
    n = 100_000
    t0 = time.perf_counter()
    for i in range(n):
        probe.on_boundary(i, i * 100)
    per_beat_us = 1e6 * (time.perf_counter() - t0) / n

    # window-close microcost, measured directly with the FULL production
    # wiring (phases snapshot + row publish + SLO evaluate + fleet
    # aggregate + fleet.json rewrite): window=1 makes every boundary a
    # close. This is the honest per-window number — the wall A/B above is
    # bistable +/-20% on the shared 1-core bench host (runs straddle zero),
    # so the microcosts are what the in-suite contract test enforces.
    tmp2 = tempfile.mkdtemp(prefix="w2v_signal_close_")
    closer = SignalEngine(
        window=1, phases=trainer.phases, flight=trainer.flight,
        metrics_dir=tmp2, host=0,
        slo=SloEvaluator(parse_slo("throughput_wps<0.5*baseline:for=3")),
        aggregator=FleetAggregator(tmp2, window_steps=1),
    )
    n_close = 200
    t0 = time.perf_counter()
    for i in range(1, n_close + 1):
        closer.on_boundary(i, i * 100)
    per_close_ms = 1e3 * (time.perf_counter() - t0) / n_close
    closer.close()

    base = statistics.median(base_walls)
    wired = statistics.median(wired_walls)
    overhead_pct = 100.0 * (wired - base) / base
    # min-wall overhead: the noise-robust same-work estimator — host
    # contention only ever ADDS time, so the minima are the cleanest
    # observation of each leg on a shared host
    min_overhead_pct = 100.0 * (min(wired_walls) - min(base_walls)) / min(
        base_walls
    )
    dev = jax.devices()[0]
    print(json.dumps({
        "metric": f"derived-signal plane overhead "
                  f"({args.tokens // 1000}k zipf, {dev.platform})",
        "value": round(overhead_pct, 2),
        "unit": "% wall",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "steps_per_run": steps,
        "windows_per_run": windows,
        "signal_window_steps": args.window,
        "reps": args.reps,
        "base_wall_s": [round(w, 3) for w in base_walls],
        "wired_wall_s": [round(w, 3) for w in wired_walls],
        "median_base_s": round(base, 3),
        "median_wired_s": round(wired, 3),
        "min_overhead_pct": round(min_overhead_pct, 2),
        "p50_step_ms": round(p50_step_ms, 3),
        "beat_cost_us": round(per_beat_us, 3),
        "beat_cost_pct_of_step": round(
            100.0 * per_beat_us / (1e3 * p50_step_ms), 4
        ),
        "close_cost_ms": round(per_close_ms, 3),
        # one close amortizes over `window` steps: its share of window wall
        "close_cost_pct_of_window": round(
            100.0 * per_close_ms / (args.window * p50_step_ms), 4
        ),
    }))


if __name__ == "__main__":
    main()
