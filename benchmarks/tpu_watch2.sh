#!/bin/bash
# Wait for the TPU tunnel, then run the round-2 measurement sweep.
cd "$(dirname "$0")/.."
OUT=benchmarks/TPU_R2
probe() { timeout 60 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; }
echo "watch2 start $(date)" >> $OUT/sweep2.txt
n=0
until probe; do
  n=$((n+1)); sleep 110
done
echo "tunnel up after $n waits $(date)" >> $OUT/sweep2.txt
for args in \
  "" \
  "--resident 0" \
  "--chunk-cap 96" \
  "--batch-rows 512" \
  "--kp 32" \
  "--batch-rows 512 --kp 32" \
  ; do
  echo "=== bench $args" >> $OUT/sweep2.txt
  timeout 900 python bench.py $args --probe-retries 1 2>/dev/null | tail -1 >> $OUT/sweep2.txt
done
echo "=== trace capture" >> $OUT/sweep2.txt
timeout 600 python benchmarks/trace_tools.py capture --out /tmp/tr_r2 >> $OUT/trace_capture.out 2>&1
timeout 300 python benchmarks/trace_tools.py report /tmp/tr_r2 > $OUT/trace_report.txt 2>&1
echo DONE >> $OUT/sweep2.txt
